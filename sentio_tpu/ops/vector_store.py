"""Pluggable vector stores: the in-tree TPU dense index and an external
Qdrant adapter, behind one registry.

Parity with the reference's store layer (src/core/vector_store/__init__.py:
17-57 there — registry of named backends; qdrant_store.py:37-523 — the
LangChain-style Qdrant wrapper with collection bootstrap, upsert, filtered
search, and health check). Differences, TPU-first:

* The DEFAULT store is :class:`sentio_tpu.ops.dense_index.TpuDenseIndex` —
  corpus embeddings live in HBM sharded over the mesh and top-k is an XLA
  matmul, replacing the external ANN server for NQ-scale corpora
  (SURVEY.md §2.6 "TPU-native plan").
* The Qdrant adapter targets Qdrant's REST API directly over httpx — the
  ``qdrant-client`` package is not a dependency. It exists as the escape
  hatch for corpora too large for HBM (SURVEY.md §7 "exact-vs-ANN
  tradeoff") and converts payloads to :class:`Document` with the same
  multi-key text fallback the reference applies (dense.py:76-104 there).

Both stores expose the surface the retrieval/ingest layers consume:
``add/delete/clear/size/documents/search/search_batch/retrieve``. Document
ids are arbitrary strings; Qdrant requires UUID/int point ids, so point ids
are UUIDv5 hashes of the document id and the original id rides in the
payload.
"""

from __future__ import annotations

import itertools
import logging
import threading
import uuid
from typing import Any, Optional, Protocol, Sequence

import numpy as np

from sentio_tpu.models.document import Document

logger = logging.getLogger(__name__)

_UUID_NS = uuid.UUID("8a6e0804-2bd0-4672-b79d-d97027f9071a")


class VectorStore(Protocol):
    """What retrieval (ops/retrievers.py) and ingest (ops/ingest.py) need."""

    dim: int

    @property
    def size(self) -> int: ...
    def documents(self) -> list[Document]: ...
    def add(self, documents: Sequence[Document], embeddings: np.ndarray) -> None: ...
    def delete(self, ids: Sequence[str]) -> int: ...
    def clear(self) -> None: ...
    def search(self, query: np.ndarray, top_k: int = 10) -> list[tuple[Document, float]]: ...
    def search_batch(
        self, queries: np.ndarray, top_k: int = 10
    ) -> list[list[tuple[Document, float]]]: ...
    def retrieve(self, query_embedding: np.ndarray, top_k: int = 10) -> list[Document]: ...


class VectorStoreError(Exception):
    pass


def _point_id(doc_id: str) -> str:
    return str(uuid.uuid5(_UUID_NS, doc_id))


def _payload_to_document(payload: dict, point_id: str) -> Document:
    """Payload → Document with the reference's multi-key text fallback
    (payloads written by other tools may use different content keys)."""
    text = ""
    for key in ("text", "content", "page_content", "body"):
        val = payload.get(key)
        if isinstance(val, str) and val:
            text = val
            break
    meta = payload.get("metadata")
    if not isinstance(meta, dict):
        meta = {k: v for k, v in payload.items() if k not in ("text", "content", "page_content", "body", "doc_id")}
    return Document(text=text, id=str(payload.get("doc_id") or point_id), metadata=dict(meta))


class TransientStoreError(VectorStoreError):
    """Connection failures and 5xx — retried; 4xx are not."""


class QdrantVectorStore:
    """External Qdrant collection over its REST API (httpx, no client lib).

    Synchronous by design: retrieval already runs retriever legs in worker
    threads, and one HTTP round-trip per search matches the reference's
    behavior (qdrant_store.py:351-417 there). Collection is bootstrapped on
    first use with cosine distance — embeddings are L2-normalized by the
    embedder, so ranking matches the TPU index's inner product.

    Concurrency parity with the reference's pooled async client
    (async_qdrant_store.py:50-266 there — pool of 2-4 clients, 30 s health
    loop, per-op breaker+retry):

    * ``pool_size`` persistent httpx clients (each with its own keep-alive
      connection pool) checked out round-robin, so concurrent retrieval
      legs never serialize on one connection and a wedged socket degrades
      1/N of traffic, not all of it;
    * every operation runs breaker(retry(op)): transport errors and 5xx
      retry with jittered backoff, then count against a named circuit
      breaker (visible on /health/detailed with every other breaker);
    * a daemon health loop probes ``/collections`` every
      ``health_interval_s`` and caches the verdict — ``health()`` answers
      from the cache instead of spending a round trip per health check.
    """

    def __init__(
        self,
        dim: int,
        url: str = "http://localhost:6333",
        collection: str = "sentio",
        api_key: str = "",
        timeout_s: float = 10.0,
        transport: Any = None,  # tests inject httpx.MockTransport
        pool_size: int = 3,
        health_interval_s: float = 30.0,
        retry: Optional["RetryPolicy"] = None,
    ) -> None:
        import httpx

        from sentio_tpu.infra.resilience import CircuitBreaker, RetryPolicy

        self.dim = dim
        self.collection = collection
        headers = {"api-key": api_key} if api_key else {}
        self._clients = [
            httpx.Client(
                base_url=url.rstrip("/"), headers=headers, timeout=timeout_s,
                transport=transport,
            )
            for _ in range(max(int(pool_size), 1))
        ]
        self._rr = itertools.count()
        self._breaker = CircuitBreaker(
            name=f"qdrant:{collection}", failure_threshold=5,
            recovery_timeout_s=max(health_interval_s, 5.0),
        )
        self._retry = retry or RetryPolicy(
            max_attempts=3, base_delay_s=0.1, max_delay_s=2.0,
            retry_on=(TransientStoreError,),
        )
        self._bootstrapped = False
        self._bootstrap_lock = threading.Lock()
        self._health_interval = float(health_interval_s)
        self._healthy: Optional[bool] = None  # None until the loop reports
        self._stop = threading.Event()
        self._health_lock = threading.Lock()
        self._health_thread: Optional[threading.Thread] = None

    def _next_client(self):
        return self._clients[next(self._rr) % len(self._clients)]

    # ------------------------------------------------------------------ http

    def _raw_request(self, method: str, path: str, json_body: Optional[dict]) -> dict:
        import httpx

        try:
            resp = self._next_client().request(method, path, json=json_body)
        except httpx.HTTPError as exc:
            raise TransientStoreError(f"qdrant {method} {path}: {exc}") from exc
        if resp.status_code >= 500:
            raise TransientStoreError(
                f"qdrant {method} {path} -> {resp.status_code}: {resp.text[:300]}"
            )
        if resp.status_code >= 400:
            raise VectorStoreError(
                f"qdrant {method} {path} -> {resp.status_code}: {resp.text[:300]}"
            )
        try:
            return resp.json()
        except ValueError as exc:
            # a 2xx non-JSON body (interposed proxy, captive portal) must
            # stay inside the VectorStoreError contract — an escaping
            # JSONDecodeError would kill the health loop thread
            raise TransientStoreError(
                f"qdrant {method} {path}: non-JSON 2xx body"
            ) from exc

    def _request(self, method: str, path: str, json_body: Optional[dict] = None) -> dict:
        self._ensure_health_loop()
        if not self._breaker.allow():
            raise VectorStoreError(f"qdrant unavailable: circuit {self._breaker.name} open")
        try:
            out = self._retry.run(self._raw_request, method, path, json_body)
        except TransientStoreError:
            self._breaker.record_failure()
            raise
        except VectorStoreError:
            # 4xx proves the backend is up and answering — a stream of
            # client errors must not open the circuit on a healthy store
            self._breaker.record_success()
            raise
        self._breaker.record_success()
        return out

    # ---------------------------------------------------------------- health

    def _ensure_health_loop(self) -> None:
        if (self._health_interval <= 0 or self._health_thread is not None
                or self._stop.is_set()):
            return
        # check-then-set under a lock: the concurrent first requests this
        # pool exists for must not each spawn a probe thread
        with self._health_lock:
            if self._health_thread is not None or self._stop.is_set():
                return
            self._health_thread = threading.Thread(
                target=self._health_loop,
                name=f"qdrant-health-{self.collection}", daemon=True,
            )
            self._health_thread.start()

    def _health_loop(self) -> None:
        # reference contract: a 30 s background probe so health answers are
        # cached, not a round trip each (async_qdrant_store.py:118-166 there)
        while not self._stop.wait(self._health_interval):
            ok = self._probe()
            if ok != self._healthy:
                logger.info(
                    "qdrant %s health: %s", self.collection,
                    "recovered" if ok else "DOWN",
                )
            self._healthy = ok

    def _probe(self) -> bool:
        try:
            # direct, un-breakered probe: the loop is how an OPEN breaker's
            # backend recovery becomes visible without live traffic
            self._raw_request("GET", "/collections", None)
            return True
        except Exception:  # noqa: BLE001 — a probe failure of ANY kind
            # (incl. RuntimeError from a closed client) must not kill the
            # health thread; it just means "not healthy right now"
            return False

    def _ensure_collection(self) -> None:
        if self._bootstrapped:
            return
        # serialized: retrieval legs run in worker threads, and two
        # concurrent first queries would otherwise both see 404 and race the
        # create (Qdrant 409s the loser). A 409 from another PROCESS racing
        # us is likewise success — the collection exists. Both the check and
        # the create ride the same breaker+retry as every other operation,
        # so a transient blip during FIRST use is absorbed, not fatal.
        with self._bootstrap_lock:
            if self._bootstrapped:
                return
            exists = True
            try:
                self._request("GET", f"/collections/{self.collection}")
            except TransientStoreError:
                raise
            except VectorStoreError as exc:
                if "-> 404" not in str(exc):
                    raise
                exists = False
            if not exists:
                try:
                    self._request(
                        "PUT",
                        f"/collections/{self.collection}",
                        {"vectors": {"size": self.dim, "distance": "Cosine"}},
                    )
                except VectorStoreError as exc:
                    if "409" not in str(exc):
                        raise
            self._bootstrapped = True

    def health(self) -> bool:
        # cached verdict once the background loop has reported; a live probe
        # only before its first tick (or with the loop disabled)
        if self._stop.is_set():
            return False  # closed stores are not healthy, cached or not
        self._ensure_health_loop()
        if self._healthy is not None:
            return self._healthy
        return self._probe()

    # ------------------------------------------------------------------ crud

    @property
    def size(self) -> int:
        self._ensure_collection()
        out = self._request(
            "POST", f"/collections/{self.collection}/points/count", {"exact": True}
        )
        return int(out["result"]["count"])

    def add(self, documents: Sequence[Document], embeddings: np.ndarray) -> None:
        embeddings = np.asarray(embeddings, np.float32)
        if embeddings.ndim != 2 or embeddings.shape[1] != self.dim:
            raise VectorStoreError(f"expected embeddings [N, {self.dim}], got {embeddings.shape}")
        if len(documents) != embeddings.shape[0]:
            raise VectorStoreError("documents/embeddings length mismatch")
        self._ensure_collection()
        points = [
            {
                "id": _point_id(doc.id),
                "vector": emb.tolist(),
                "payload": {"doc_id": doc.id, "text": doc.text, "metadata": doc.metadata},
            }
            for doc, emb in zip(documents, embeddings)
        ]
        # batch like the reference's upsert batching (async_qdrant_store.py:424-459)
        for start in range(0, len(points), 128):
            self._request(
                "PUT",
                f"/collections/{self.collection}/points?wait=true",
                {"points": points[start : start + 128]},
            )

    def delete(self, ids: Sequence[str]) -> int:
        if not ids:
            return 0
        self._ensure_collection()
        pids = [_point_id(i) for i in ids]
        # which of these actually exist (retrieve-by-ids, no payloads) —
        # counting size before/after instead would race concurrent writers
        # and cost two exact-count collection scans
        existing = self._request(
            "POST",
            f"/collections/{self.collection}/points",
            {"ids": pids, "with_payload": False, "with_vector": False},
        )
        n = len(existing.get("result") or [])
        self._request(
            "POST",
            f"/collections/{self.collection}/points/delete?wait=true",
            {"points": pids},
        )
        return n

    def clear(self) -> None:
        self._request("DELETE", f"/collections/{self.collection}")
        self._bootstrapped = False

    def documents(self) -> list[Document]:
        """Scroll the whole collection (the reference's corpus hydration,
        retrievers/factory.py:83-133 there) — feeds BM25 rebuild."""
        self._ensure_collection()
        docs: list[Document] = []
        offset = None
        while True:
            body: dict = {"limit": 256, "with_payload": True, "with_vector": False}
            if offset is not None:
                body["offset"] = offset
            out = self._request(
                "POST", f"/collections/{self.collection}/points/scroll", body
            )
            result = out["result"]
            for pt in result["points"]:
                docs.append(_payload_to_document(pt.get("payload") or {}, str(pt["id"])))
            offset = result.get("next_page_offset")
            if offset is None:
                return docs

    # ---------------------------------------------------------------- search

    def search(self, query: np.ndarray, top_k: int = 10) -> list[tuple[Document, float]]:
        self._ensure_collection()
        query = np.asarray(query, np.float32).reshape(-1)
        out = self._request(
            "POST",
            f"/collections/{self.collection}/points/search",
            {"vector": query.tolist(), "limit": int(top_k), "with_payload": True},
        )
        hits = []
        for hit in out["result"]:
            doc = _payload_to_document(hit.get("payload") or {}, str(hit["id"]))
            hits.append((doc, float(hit["score"])))
        return hits

    def search_batch(
        self, queries: np.ndarray, top_k: int = 10
    ) -> list[list[tuple[Document, float]]]:
        self._ensure_collection()
        queries = np.asarray(queries, np.float32)
        body = {
            "searches": [
                {"vector": q.tolist(), "limit": int(top_k), "with_payload": True}
                for q in queries
            ]
        }
        out = self._request(
            "POST", f"/collections/{self.collection}/points/search/batch", body
        )
        batches = []
        for result in out["result"]:
            hits = []
            for hit in result:
                doc = _payload_to_document(hit.get("payload") or {}, str(hit["id"]))
                hits.append((doc, float(hit["score"])))
            batches.append(hits)
        return batches

    def retrieve(self, query_embedding: np.ndarray, top_k: int = 10) -> list[Document]:
        out = []
        for doc, score in self.search(query_embedding, top_k):
            meta = dict(doc.metadata)
            meta["score"] = score
            meta["retriever"] = "qdrant"
            out.append(Document(text=doc.text, id=doc.id, metadata=meta))
        return out

    def close(self) -> None:
        self._stop.set()
        if self._health_thread is not None:
            self._health_thread.join(timeout=2.0)
            self._health_thread = None
        for client in self._clients:
            client.close()


def get_vector_store(
    name: str,
    dim: int,
    mesh: Any = None,
    settings: Any = None,
    **kwargs: Any,
) -> Any:
    """Registry: ``tpu`` (in-HBM exact index, default) | ``qdrant``
    (external REST adapter). Mirrors the reference's named-store factory
    (vector_store/__init__.py:17-57 there)."""
    if name == "tpu":
        from sentio_tpu.ops.dense_index import TpuDenseIndex

        dtype = kwargs.pop("dtype", "bfloat16")
        return TpuDenseIndex(dim=dim, mesh=mesh, dtype=dtype)
    if name == "qdrant":
        r = settings.retrieval if settings is not None else None
        url = kwargs.pop("url", "") or (r.qdrant_url if r else "") or "http://localhost:6333"
        collection = kwargs.pop("collection", None) or (r.collection_name if r else "sentio")
        if "api_key" not in kwargs and r is not None:
            kwargs["api_key"] = r.qdrant_api_key
        return QdrantVectorStore(dim=dim, url=url, collection=collection, **kwargs)
    raise VectorStoreError(f"unknown vector store {name!r} (expected: tpu, qdrant)")
