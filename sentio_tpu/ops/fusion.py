"""Rank-fusion for hybrid retrieval.

Same fusion math as the reference's ``HybridRetriever``
(/root/reference/src/core/retrievers/hybrid.py:204-259): ``rrf``,
``weighted_rrf``, and ``comb_sum`` with per-list min-max normalization.
Inputs are ranked Document lists from independent retrieval legs (dense leg
on TPU, sparse leg on host CPU); output is a single deduplicated list with
``hybrid_score`` and ``score`` metadata, sorted descending. Pure host-side
functions — fusion over <=100 candidates is not device work.
"""

from __future__ import annotations

from typing import Optional, Sequence

from sentio_tpu.models.document import Document

FUSION_METHODS = ("rrf", "weighted_rrf", "comb_sum")


def _minmax(scores: list[float]) -> list[float]:
    if not scores:
        return scores
    lo, hi = min(scores), max(scores)
    if hi - lo < 1e-12:
        return [1.0 for _ in scores]
    return [(s - lo) / (hi - lo) for s in scores]


def fuse(
    result_lists: Sequence[Sequence[Document]],
    method: str = "rrf",
    weights: Optional[Sequence[float]] = None,
    rrf_k: int = 60,
    top_k: Optional[int] = None,
) -> list[Document]:
    """Fuse ranked lists into one. Deduplicates by document id, merging
    metadata with earlier lists taking precedence on conflicts."""
    if method not in FUSION_METHODS:
        raise ValueError(f"unknown fusion method {method!r}; expected one of {FUSION_METHODS}")
    if weights is None:
        weights = [1.0] * len(result_lists)
    if len(weights) != len(result_lists):
        raise ValueError("weights length must match number of result lists")

    fused: dict[str, float] = {}
    docs: dict[str, Document] = {}

    for li, results in enumerate(result_lists):
        w = float(weights[li])
        if method == "comb_sum":
            raw = [d.score() for d in results]
            normed = _minmax(raw)
            contributions = [w * s for s in normed]
        else:  # rrf / weighted_rrf operate on ranks only
            w_eff = w if method == "weighted_rrf" else 1.0
            contributions = [w_eff / (rrf_k + rank + 1) for rank in range(len(results))]
        for doc, contrib in zip(results, contributions):
            fused[doc.id] = fused.get(doc.id, 0.0) + contrib
            if doc.id in docs:
                merged = dict(doc.metadata)
                merged.update(docs[doc.id].metadata)
                docs[doc.id].metadata = merged
            else:
                docs[doc.id] = Document(text=doc.text, metadata=dict(doc.metadata), id=doc.id)

    ranked = sorted(fused.items(), key=lambda kv: kv[1], reverse=True)
    if top_k is not None:
        ranked = ranked[:top_k]
    out = []
    for doc_id, score in ranked:
        doc = docs[doc_id]
        doc.metadata["hybrid_score"] = score
        doc.metadata["score"] = score
        out.append(doc)
    return out
