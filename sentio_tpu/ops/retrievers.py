"""Retrievers: dense (TPU index), sparse (host BM25), and hybrid fusion.

Parity with /root/reference/src/core/retrievers/: ``BaseRetriever`` ABC with
an async wrapper (base.py:29-42), dense retrieval (dense.py:21-119 — but the
embedding is an in-process TPU forward and the store is the in-HBM exact
index instead of Qdrant-over-HTTP), BM25 (sparse.py), and the hybrid fuser
(hybrid.py:48-324) with rrf/weighted_rrf/comb_sum and post-fusion scorer
plugins. The dense and sparse legs run concurrently — device matmul and host
CPU scoring overlap (`asyncio.gather` over the executor).
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass, field
from typing import Optional, Sequence

import numpy as np

from sentio_tpu.config import RetrievalConfig, Settings, get_settings
from sentio_tpu.infra import faults
from sentio_tpu.models.document import Document
from sentio_tpu.ops.bm25 import BM25Index
from sentio_tpu.ops.dense_index import TpuDenseIndex
from sentio_tpu.ops.fusion import fuse
from sentio_tpu.ops.scorers import ScorerPlugin


class RetrieverError(Exception):
    pass


class BaseRetriever:
    """retrieve(query, top_k) → ranked Documents; aretrieve = executor wrap."""

    name = "base"

    def retrieve(self, query: str, top_k: int = 10) -> list[Document]:
        raise NotImplementedError

    async def aretrieve(self, query: str, top_k: int = 10) -> list[Document]:
        return await asyncio.get_running_loop().run_in_executor(
            None, self.retrieve, query, top_k
        )


@dataclass
class DenseRetriever(BaseRetriever):
    embedder: object
    index: TpuDenseIndex
    name: str = "dense"

    def retrieve(self, query: str, top_k: int = 10) -> list[Document]:
        faults.hit("retriever.dense")
        # fused path: embedder output stays on device and feeds the index's
        # top-k program directly — one host round trip for the whole leg
        if hasattr(self.embedder, "embed_device") and isinstance(self.index, TpuDenseIndex):
            q_dev = self.embedder.embed_device([query])
            return [doc for doc, _ in self._scored(q_dev, top_k)]
        q_vec = self.embedder.embed(query)
        return self.index.retrieve(np.asarray(q_vec, np.float32), top_k)

    def _scored(self, q_dev, top_k: int):
        out = []
        for doc, score in self.index.search_batch(q_dev, top_k)[0]:
            meta = dict(doc.metadata)
            meta["score"] = score
            meta["retriever"] = "dense"
            out.append((Document(text=doc.text, metadata=meta, id=doc.id), score))
        return out


@dataclass
class SparseRetriever(BaseRetriever):
    index: BM25Index
    name: str = "bm25"

    def retrieve(self, query: str, top_k: int = 10) -> list[Document]:
        faults.hit("retriever.sparse")
        return self.index.retrieve(query, top_k)


@dataclass
class HybridRetriever(BaseRetriever):
    """Fuses any number of legs. Candidate pools are over-fetched (top_k * 2,
    min 10) before fusion so the fused head has depth, matching the
    reference's pool-then-truncate behavior.

    ``web_cache`` (optional) is the reference's cached-web-results pre-hit
    (/root/reference/src/core/retrievers/hybrid.py:96-107,146-182): a
    secondary collection consulted alongside the legs whose hits are
    PREPENDED to the dense leg before fusion, so previously fetched web
    results outrank fresh corpus hits at equal rank. A failing cache leg
    degrades silently, like every other leg."""

    retrievers: Sequence[BaseRetriever] = ()
    config: RetrievalConfig = field(default_factory=RetrievalConfig)
    scorers: Sequence[ScorerPlugin] = ()
    web_cache: Optional[BaseRetriever] = None
    name: str = "hybrid"

    def _weights(self) -> list[float]:
        table = {"dense": self.config.dense_weight, "bm25": self.config.sparse_weight}
        return [table.get(r.name, 1.0) for r in self.retrievers]

    def retrieve(self, query: str, top_k: int = 10) -> list[Document]:
        return asyncio.run(self.aretrieve(query, top_k))

    async def aretrieve(self, query: str, top_k: int = 10) -> list[Document]:
        pool = max(top_k * 2, 10)
        fetchers = [r.aretrieve(query, pool) for r in self.retrievers]
        if self.web_cache is not None:
            fetchers.append(self.web_cache.aretrieve(query, pool))
        legs = await asyncio.gather(*fetchers, return_exceptions=True)
        cache_hits: list[Document] = []
        if self.web_cache is not None:
            cache_leg = legs[-1]
            legs = legs[:-1]
            if not isinstance(cache_leg, Exception):
                cache_hits = list(cache_leg)
        ok_lists: list[list[Document]] = []
        ok_weights: list[float] = []
        ok_names: list[str] = []
        for retriever, leg, weight in zip(self.retrievers, legs, self._weights()):
            if isinstance(leg, Exception):
                continue  # degraded: a failed leg drops out, fusion continues
            ok_lists.append(leg)
            ok_weights.append(weight)
            ok_names.append(getattr(retriever, "name", ""))
        if cache_hits:
            # prepend to the dense leg (ref hybrid.py:213 `all_dense_hits =
            # dense_cache_hits + dense_hits`), deduped by id, cache first
            if "dense" in ok_names:
                j = ok_names.index("dense")
                seen = {d.id for d in cache_hits}
                ok_lists[j] = cache_hits + [d for d in ok_lists[j] if d.id not in seen]
            else:  # no dense leg survived: the cache rides as its own leg
                ok_lists.append(cache_hits)
                ok_weights.append(self.config.dense_weight)
        if not ok_lists:
            raise RetrieverError("all retrieval legs failed")
        fused = fuse(
            ok_lists,
            method=self.config.fusion_method,
            weights=ok_weights,
            rrf_k=self.config.rrf_k,
        )
        fused = self._apply_scorers(query, fused)
        return fused[:top_k]

    def _apply_scorers(self, query: str, docs: list[Document]) -> list[Document]:
        if not self.scorers or not docs:
            return docs
        base = np.asarray([d.score() for d in docs], np.float32)
        lo, hi = float(base.min()), float(base.max())
        mixed = (base - lo) / (hi - lo) if hi > lo else np.ones_like(base)
        total_w = 1.0
        for scorer in self.scorers:
            try:
                s = scorer.score(query, docs)
            except Exception:  # noqa: BLE001 — a broken plugin never kills retrieval
                continue  # a broken plugin never kills retrieval
            mixed = mixed + scorer.weight * np.asarray(s, np.float32)
            total_w += scorer.weight
        mixed = mixed / total_w
        order = np.argsort(-mixed, kind="stable")
        out = []
        for rank, i in enumerate(order):
            doc = docs[int(i)]
            doc.metadata["hybrid_score"] = float(mixed[int(i)])
            doc.metadata["score"] = float(mixed[int(i)])
            out.append(doc)
        return out


def create_retriever(
    settings: Optional[Settings] = None,
    embedder=None,
    dense_index: Optional[TpuDenseIndex] = None,
    bm25_index: Optional[BM25Index] = None,
    scorers: Optional[Sequence[ScorerPlugin]] = None,
    web_cache_index: Optional[TpuDenseIndex] = None,
) -> BaseRetriever:
    """Strategy registry (reference: retrievers/factory.py:21-196): ``dense``,
    ``bm25``, or ``hybrid`` from config; hybrid tolerates a missing leg and
    consults the optional cached-web-results index before fusing."""
    settings = settings or get_settings()
    strategy = settings.retrieval.strategy
    dense = DenseRetriever(embedder, dense_index) if embedder is not None and dense_index is not None else None
    sparse = SparseRetriever(bm25_index) if bm25_index is not None else None

    if strategy == "dense":
        if dense is None:
            raise RetrieverError("dense strategy needs embedder + dense_index")
        return dense
    if strategy in ("bm25", "sparse"):
        if sparse is None:
            raise RetrieverError("bm25 strategy needs a BM25 index")
        return sparse
    if strategy == "hybrid":
        legs = [r for r in (dense, sparse) if r is not None]
        if not legs:
            raise RetrieverError("hybrid strategy needs at least one leg")
        web_cache = None
        if web_cache_index is not None and embedder is not None:
            web_cache = DenseRetriever(embedder, web_cache_index, name="web_cache")
        return HybridRetriever(
            retrievers=legs,
            config=settings.retrieval,
            scorers=scorers or (),
            web_cache=web_cache,
        )
    raise RetrieverError(f"unknown retrieval strategy {strategy!r}")
