"""Typed configuration tree for the whole framework.

The reference scatters ~60 env-aliased pydantic-settings fields plus ad-hoc
``os.getenv`` at use sites (/root/reference/src/utils/settings.py:27-191,
retrievers/factory.py:35-48). Here there is ONE typed tree, built once from
the environment via :func:`Settings.from_env`, with the reference's env names
kept as aliases so existing deployments carry over — plus a TPU section the
reference never needed (mesh shape, dtype, KV paging, batching deadline).

No pydantic dependency at this layer: plain dataclasses keep import cost ~0
and make the tree trivially picklable into worker processes.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field, replace
from typing import Optional, Sequence

__all__ = [
    "ChunkingConfig",
    "RetrievalConfig",
    "RerankConfig",
    "GeneratorConfig",
    "EmbedderConfig",
    "MeshConfig",
    "ServeConfig",
    "CacheConfig",
    "AuthConfig",
    "ObservabilityConfig",
    "Settings",
    "get_settings",
    "set_settings",
]


def _env_str(names: Sequence[str], default: str) -> str:
    for name in names:
        value = os.environ.get(name)
        if value is not None and value != "":
            return value
    return default


def _env_int(names: Sequence[str], default: int) -> int:
    raw = _env_str(names, "")
    if not raw:
        return default
    try:
        return int(raw)
    except ValueError:
        return default


def _env_float(names: Sequence[str], default: float) -> float:
    raw = _env_str(names, "")
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        return default


def _env_bool(names: Sequence[str], default: bool) -> bool:
    raw = _env_str(names, "").strip().lower()
    if not raw:
        return default
    return raw in ("1", "true", "yes", "on")


@dataclass
class ChunkingConfig:
    """Splitter settings (reference: chunking/text_splitter.py:23-80)."""

    strategy: str = "recursive"  # recursive | fixed | sentence
    chunk_size: int = 512
    chunk_overlap: int = 64

    @classmethod
    def from_env(cls) -> "ChunkingConfig":
        return cls(
            strategy=_env_str(["CHUNKING_STRATEGY"], "recursive"),
            chunk_size=_env_int(["CHUNK_SIZE"], 512),
            chunk_overlap=_env_int(["CHUNK_OVERLAP"], 64),
        )


@dataclass
class RetrievalConfig:
    """Retriever strategy + fusion knobs (reference: retrievers/factory.py:21-196)."""

    strategy: str = "hybrid"  # dense | bm25 | hybrid
    top_k: int = 10
    rrf_k: int = 60
    fusion_method: str = "rrf"  # rrf | weighted_rrf | comb_sum
    dense_weight: float = 0.7
    sparse_weight: float = 0.3
    # scorer plugin stack (reference default weights 0.8/0.2/0.5, factory.py:64-80)
    use_scorers: bool = False
    keyword_scorer_weight: float = 0.8
    recency_scorer_weight: float = 0.2
    mmr_scorer_weight: float = 0.5
    mmr_lambda: float = 0.7
    # BM25 parameters (Okapi defaults; pyserini used k1=0.9 b=0.4 at scale)
    bm25_k1: float = 1.5
    bm25_b: float = 0.75
    bm25_backend: str = "auto"  # auto | numpy | native
    # dense index
    index_backend: str = "tpu"  # tpu | qdrant
    collection_name: str = "sentio"
    qdrant_url: str = "http://localhost:6333"
    qdrant_api_key: str = ""
    # persisted TpuDenseIndex to load at startup ("" = start empty); BM25
    # rehydrates from the loaded documents
    index_path: str = ""
    # persisted cached-web-results index consulted before fusion (reference
    # CACHE_COLLECTION_NAME "web_cache", hybrid.py:96-107 there); "" = off
    web_cache_path: str = ""

    @classmethod
    def from_env(cls) -> "RetrievalConfig":
        return cls(
            strategy=_env_str(["RETRIEVAL_STRATEGY", "RETRIEVER_TYPE"], "hybrid"),
            top_k=_env_int(["RETRIEVAL_TOP_K", "TOP_K"], 10),
            rrf_k=_env_int(["RRF_K"], 60),
            fusion_method=_env_str(["FUSION_METHOD", "HYBRID_FUSION"], "rrf"),
            dense_weight=_env_float(["DENSE_WEIGHT"], 0.7),
            sparse_weight=_env_float(["SPARSE_WEIGHT"], 0.3),
            use_scorers=_env_bool(["USE_SCORERS"], False),
            keyword_scorer_weight=_env_float(["KEYWORD_SCORER_WEIGHT"], 0.8),
            recency_scorer_weight=_env_float(["RECENCY_SCORER_WEIGHT"], 0.2),
            mmr_scorer_weight=_env_float(["MMR_SCORER_WEIGHT"], 0.5),
            mmr_lambda=_env_float(["MMR_LAMBDA"], 0.7),
            bm25_k1=_env_float(["BM25_K1"], 1.5),
            bm25_b=_env_float(["BM25_B"], 0.75),
            bm25_backend=_env_str(["BM25_BACKEND"], "auto"),
            index_backend=_env_str(["INDEX_BACKEND", "VECTOR_STORE"], "tpu"),
            collection_name=_env_str(["COLLECTION_NAME", "QDRANT_COLLECTION"], "sentio"),
            qdrant_url=_env_str(["QDRANT_URL"], "http://localhost:6333"),
            qdrant_api_key=_env_str(["QDRANT_API_KEY"], ""),
            index_path=_env_str(["INDEX_PATH"], ""),
            web_cache_path=_env_str(["WEB_CACHE_PATH", "CACHE_COLLECTION_PATH"], ""),
        )


@dataclass
class RerankConfig:
    """Reranker selection (reference: rerankers/__init__.py:11-30, jina_reranker.py)."""

    enabled: bool = True
    kind: str = "cross_encoder"  # cross_encoder | passthrough
    top_k: int = 5
    max_pair_tokens: int = 512
    batch_size: int = 32
    # converted checkpoint (cli convert cross-encoder ...)
    checkpoint_path: str = ""
    tokenizer_path: str = ""

    @classmethod
    def from_env(cls) -> "RerankConfig":
        return cls(
            enabled=_env_bool(["USE_RERANKER"], True),
            kind=_env_str(["RERANKER_KIND", "RERANKER_TYPE"], "cross_encoder"),
            top_k=_env_int(["RERANK_TOP_K"], 5),
            max_pair_tokens=_env_int(["RERANK_MAX_PAIR_TOKENS"], 512),
            batch_size=_env_int(["RERANK_BATCH_SIZE"], 32),
            checkpoint_path=_env_str(["RERANKER_CHECKPOINT"], ""),
            tokenizer_path=_env_str(["RERANKER_TOKENIZER"], ""),
        )


@dataclass
class EmbedderConfig:
    """Bi-encoder settings. ``provider='tpu'`` is the in-process Flax model;
    ``'hash'`` is the deterministic offline fake (the reference's mock-mode
    pattern, jina.py:141-159 there) used by tests and no-hardware dev."""

    provider: str = "tpu"  # tpu | hash
    dim: int = 1024
    max_tokens: int = 512
    batch_size: int = 128
    cache_size: int = 10_000
    cache_ttl_s: float = 3600.0
    model_preset: str = "base"  # tiny | base (tiny = CPU-test scale)
    # converted checkpoint (cli convert encoder ...); "" = random-init preset
    checkpoint_path: str = ""
    tokenizer_path: str = ""  # local HF tokenizer dir (usually the HF src dir)
    # coalesce concurrent single-query embeds into one device batch
    coalesce: bool = True
    coalesce_deadline_ms: float = 5.0
    coalesce_max: int = 16

    @classmethod
    def from_env(cls) -> "EmbedderConfig":
        return cls(
            provider=_env_str(["EMBEDDER_PROVIDER", "EMBEDDING_PROVIDER"], "tpu"),
            dim=_env_int(["EMBEDDING_DIM"], 1024),
            max_tokens=_env_int(["EMBED_MAX_TOKENS"], 512),
            batch_size=_env_int(["EMBED_BATCH_SIZE"], 128),
            cache_size=_env_int(["EMBEDDING_CACHE_SIZE"], 10_000),
            cache_ttl_s=_env_float(["EMBEDDING_CACHE_TTL"], 3600.0),
            model_preset=_env_str(["EMBEDDER_PRESET"], "base"),
            checkpoint_path=_env_str(["EMBEDDER_CHECKPOINT"], ""),
            tokenizer_path=_env_str(["EMBEDDER_TOKENIZER"], ""),
            coalesce=_env_bool(["EMBED_COALESCE"], True),
            coalesce_deadline_ms=_env_float(["EMBED_COALESCE_DEADLINE_MS"], 5.0),
            coalesce_max=_env_int(["EMBED_COALESCE_MAX"], 16),
        )


@dataclass
class GeneratorConfig:
    """Generator/verifier settings (reference: llm/factory.py:14-69,
    graph/factory.py:90,145 — context budget 2000 tok, 1024 max new)."""

    provider: str = "tpu"  # tpu | echo (deterministic fake) | openai (remote API)
    model_preset: str = "llama3-8b"  # llama3-8b | tiny
    checkpoint_path: str = ""  # converted checkpoint (cli convert llama ...)
    tokenizer_path: str = ""  # local HF tokenizer dir
    # speculative decoding: a small same-vocab draft checkpoint accelerates
    # temperature-0 generation on the contiguous path (greedy-exact —
    # runtime/speculative.py); empty = disabled
    draft_checkpoint_path: str = ""
    speculative_k: int = 4
    # remote OpenAI-compatible endpoint (provider="openai" — the reference's
    # primary path, kept here as the pluggable fallback seam)
    api_base: str = ""
    api_key: str = ""
    api_model: str = "default"
    api_timeout_s: float = 60.0
    mode: str = "balanced"  # fast | balanced | quality | creative
    max_new_tokens: int = 1024
    context_token_budget: int = 2000
    max_prompt_tokens: int = 4096
    use_verifier: bool = True
    verifier_max_tokens: int = 512
    # confidence-gated / async verification (ops/confidence.py):
    #   sync  — verify blocks the response (the reference behavior);
    #   async — the answer returns immediately, verify runs detached and
    #           the verdict lands on the flight record (/debug/flight/{id};
    #           SSE streams get a trailing `verify` event after done);
    #   gated — confidence >= verify_confidence_threshold short-circuits
    #           with a typed `skipped_confident` verdict (zero verify
    #           decode); below-threshold requests take the async path
    verify_mode: str = "sync"  # sync | async | gated
    verify_confidence_threshold: float = 0.75
    dtype: str = "bfloat16"
    kv_page_size: int = 128
    kv_max_pages_per_seq: int = 64
    # "int8" stores KV pages quantized (per-vector absmax scales): ~half the
    # pool HBM and decode-read bandwidth, at ~1 percent attention-score error
    kv_quant: str = "none"
    # automatic radix prefix cache (runtime/radix.py): every admission
    # longest-prefix-matches against cached KV page runs and prefills only
    # its unmatched suffix; PREFIX_CACHE=0 restores plain whole-prompt
    # admission byte-for-byte
    prefix_cache: bool = True
    max_batch_size: int = 8
    # paged KV + continuous batching as the live /chat decode path; the
    # contiguous engine remains for streaming and as an escape hatch
    use_paged_decode: bool = True
    # decode sub-steps fused into one device dispatch per engine tick —
    # amortizes host round trips; admission waits at most one tick. With an
    # empty queue the engine grows ticks toward the max so long generations
    # cost few host fetches (the per-tick fetch is ~RTT on remote devices)
    decode_steps_per_tick: int = 16
    decode_max_tick_steps: int = 64
    # 2 = dispatch tick N+1 before fetching tick N (host round trip overlaps
    # device compute; results lag one tick). 1 = synchronous ticks.
    decode_pipeline_depth: int = 2
    # chunked prefill: prompts longer than this admit one page-aligned
    # segment per tick so a long (4-8K) prefill never stalls other slots'
    # decode for its full length. 0 = off (whole-prompt admission).
    prefill_chunk: int = 0
    prefill_buckets: tuple[int, ...] = (256, 512, 1024, 2048, 4096)
    temperature_by_mode: tuple[tuple[str, float], ...] = (
        ("fast", 0.0),
        ("balanced", 0.3),
        ("quality", 0.2),
        ("creative", 0.7),
    )

    def temperature(self, mode: Optional[str] = None) -> float:
        table = dict(self.temperature_by_mode)
        return table.get(mode or self.mode, 0.3)

    @classmethod
    def from_env(cls) -> "GeneratorConfig":
        return cls(
            provider=_env_str(["LLM_PROVIDER", "CHAT_LLM_PROVIDER"], "tpu"),
            model_preset=_env_str(["LLM_MODEL", "CHAT_LLM_MODEL"], "llama3-8b"),
            checkpoint_path=_env_str(["LLM_CHECKPOINT", "MODEL_PATH"], ""),
            tokenizer_path=_env_str(["LLM_TOKENIZER", "TOKENIZER_PATH"], ""),
            draft_checkpoint_path=_env_str(["LLM_DRAFT_CHECKPOINT"], ""),
            speculative_k=_env_int(["SPECULATIVE_K"], 4),
            api_base=_env_str(["OPENAI_BASE_URL", "CHAT_LLM_BASE_URL"], ""),
            api_key=_env_str(["OPENAI_API_KEY", "CHAT_LLM_API_KEY"], ""),
            api_model=_env_str(["OPENAI_MODEL", "CHAT_LLM_API_MODEL"], "default"),
            api_timeout_s=_env_float(["OPENAI_TIMEOUT_S"], 60.0),
            mode=_env_str(["LLM_MODE"], "balanced"),
            max_new_tokens=_env_int(["LLM_MAX_TOKENS", "MAX_NEW_TOKENS"], 1024),
            context_token_budget=_env_int(["CONTEXT_TOKEN_BUDGET"], 2000),
            max_prompt_tokens=_env_int(["MAX_PROMPT_TOKENS"], 4096),
            use_verifier=_env_bool(["USE_VERIFIER"], True),
            verifier_max_tokens=_env_int(["VERIFIER_MAX_TOKENS"], 512),
            verify_mode=_env_str(["VERIFY_MODE"], "sync"),
            verify_confidence_threshold=_env_float(
                ["VERIFY_CONFIDENCE_THRESHOLD"], 0.75
            ),
            dtype=_env_str(["LLM_DTYPE"], "bfloat16"),
            kv_page_size=_env_int(["KV_PAGE_SIZE"], 128),
            kv_max_pages_per_seq=_env_int(["KV_MAX_PAGES_PER_SEQ"], 64),
            kv_quant=_env_str(["KV_QUANT"], "none"),
            prefix_cache=_env_bool(["PREFIX_CACHE"], True),
            max_batch_size=_env_int(["LLM_MAX_BATCH"], 8),
            use_paged_decode=_env_bool(["USE_PAGED_KV", "USE_PAGED_DECODE"], True),
            decode_steps_per_tick=_env_int(["DECODE_STEPS_PER_TICK"], 16),
            decode_max_tick_steps=_env_int(["DECODE_MAX_TICK_STEPS"], 64),
            decode_pipeline_depth=_env_int(["DECODE_PIPELINE_DEPTH"], 2),
            prefill_chunk=_env_int(["PREFILL_CHUNK"], 0),
        )


@dataclass
class MeshConfig:
    """TPU mesh geometry. Axes: ``dp`` (data/batch over ICI), ``tp`` (tensor
    sharding of model weights), ``sp`` (sequence/context parallel), ``pp``
    (pipeline stages over layers), ``ep`` (expert parallel for MoE layers).
    A zero means "infer from available devices" (all devices on dp unless
    tp_size set). Multi-slice deployments add a leading ``dcn`` data axis."""

    dp_size: int = 0
    tp_size: int = 1
    sp_size: int = 1
    pp_size: int = 1
    ep_size: int = 1
    dcn_size: int = 1
    backend: str = ""  # "" = jax default; "cpu" to force host platform

    @classmethod
    def from_env(cls) -> "MeshConfig":
        return cls(
            dp_size=_env_int(["MESH_DP"], 0),
            tp_size=_env_int(["MESH_TP"], 1),
            sp_size=_env_int(["MESH_SP"], 1),
            pp_size=_env_int(["MESH_PP"], 1),
            ep_size=_env_int(["MESH_EP"], 1),
            dcn_size=_env_int(["MESH_DCN"], 1),
            backend=_env_str(["MESH_BACKEND"], ""),
        )


@dataclass
class ServeConfig:
    """HTTP serving surface (reference: api/app.py:81-101, 250-281)."""

    host: str = "0.0.0.0"
    port: int = 8000
    # per-IP sliding-window limits (reference: 10/min /embed, 100/min rest)
    rate_limit_embed_per_min: int = 10
    rate_limit_default_per_min: int = 100
    max_question_chars: int = 2000
    max_embed_chars: int = 50_000
    top_k_max: int = 20
    cors_origins: str = "*"
    # only honor X-Forwarded-For when deployed behind a trusted proxy
    trust_proxy_headers: bool = False
    # request coalescing for the TPU batcher
    batch_deadline_ms: float = 8.0
    batch_max_size: int = 8
    # /upload multipart body cap (binary documents: pdf/docx)
    max_upload_mb: int = 32
    # overload & deadline controls for the paged decode service:
    # default per-request deadline (ms) applied when the caller sends none
    # (X-Deadline-Ms header / deadline_ms body field); 0 = no default
    default_deadline_ms: float = 0.0
    # admission bound on waiting decode work (inbox + admitted); 0 = derive
    # from the engine (max(8 * max_slots, 64))
    admission_max_queue: int = 0
    # crash containment: requeues granted per request after a failed decode
    # tick whose engine reset succeeded
    crash_retry_budget: int = 1
    # graceful-shutdown drain window: in-flight requests get this long to
    # finish after the server stops admitting
    drain_deadline_s: float = 10.0
    # ---- multi-replica serving tier (runtime/replica.py) ----
    # number of independent engine+service replicas behind the router;
    # 1 = today's single-engine behavior. On a mesh, replicas map onto
    # slices of the dp axis (REPLICAS must divide MESH_DP).
    replicas: int = 1
    # replica isolation tier: "thread" (default — N engine+service+pump
    # replicas inside this process, byte-compatible with every pre-process
    # behavior) or "process" (each replica is a spawned WORKER PROCESS
    # running its own engine+service+pump behind a thin RPC shim,
    # runtime/worker.py — a replica death is a real OS process death, and
    # N pumps stop contending for one GIL)
    replica_mode: str = "thread"
    # radix-affinity stickiness: a prefix-hit replica keeps the request
    # while its backlog <= stickiness x its slot count; 0 = pure
    # least-loaded routing
    affinity_stickiness: float = 4.0
    # prompt-head tokens the router matches against each replica's radix
    # cache (longer prefixes still fully reuse inside the replica)
    route_prefix_tokens: int = 512
    # per-tenant WFQ: "tenantA:4,tenantB:1" weight overrides; unlisted
    # tenants get the default weight
    tenant_weights: str = ""
    tenant_default_weight: float = 1.0
    # token-weighted deficit counters: refill rate per unit weight
    # (0 = quota-only fairness, the deterministic default) and burst cap
    tenant_refill_tokens_per_s: float = 0.0
    tenant_burst_tokens: int = 8192
    # queue slots no single tenant's quota may consume (landing room for
    # new tenants); <0 = derive max(1, capacity // 8)
    tenant_headroom: int = -1
    # batch-priority tier sheds once total pending crosses this fraction
    # of the set's capacity (interactive may use the full capacity)
    batch_shed_fraction: float = 0.8
    # ---- replica failure domains (supervision / breaker / rebuild) ----
    # arm the per-set supervisor thread (health state machine + in-place
    # rebuild of quarantined replicas); 0 only for debugging
    replica_supervise: bool = True
    # supervisor poll cadence: breaker evaluation + rebuild scheduling
    replica_probe_interval_s: float = 0.25
    # per-replica breaker: sliding window for both the caller-observed
    # error rate and the tick-failure burst count
    replica_breaker_window_s: float = 30.0
    # quarantine when failures/samples >= rate with at least min samples
    replica_breaker_error_rate: float = 0.5
    replica_breaker_min_samples: int = 4
    # quarantine on this many failed decode ticks inside the window
    replica_breaker_tick_failures: int = 3
    # base backoff between FAILED rebuild attempts (doubles per failure,
    # capped at 60s; the first rebuild try after quarantine is immediate)
    replica_quarantine_backoff_s: float = 0.5
    # failed rebuild attempts beyond this budget idle at the max backoff
    replica_rebuild_budget: int = 3
    # grace given to an error-rate-quarantined (still working) replica's
    # in-flight requests before its rebuild swaps the service out
    replica_rebuild_drain_s: float = 5.0
    # ReplicaSet-layer failover retries per request after a replica dies
    # under it (PR 5's crash retry budget, lifted across replicas)
    replica_failover_budget: int = 1
    # resume-by-replay budget for DELIVERED-token streams (mid-flight
    # failover: the delivered prefix replays onto a survivor and decode
    # continues from the splice point): -1 follows the failover budget,
    # 0 disables resumption and keeps the typed mid-stream error
    stream_resume_budget: int = -1
    # ---- stall detection & watchdog ----
    # wall-clock budget one pump loop iteration may take before the
    # watchdog declares the replica STALLED (heartbeat stale with pending
    # work) and quarantines it — must comfortably exceed the slowest
    # legitimate tick INCLUDING a cold XLA compile; 0 disables
    tick_stall_budget_s: float = 120.0
    # watchdog stand-down bound for a replica's WARMING phase: a wedge
    # DURING warmup quarantines (typed, supervisor-visible) once warmup
    # has run this long, instead of hanging the spawn/rebuild path until
    # caller timeouts fire; 0 = warmup exempt forever (pre-budget behavior)
    warmup_budget_s: float = 600.0
    # bounded rebuild worker pool: detection cadence stays at the
    # supervisor's probe interval while rebuilds (seconds-to-minutes of
    # drain + compile, or wedged entirely) run on workers; 0 = rebuild on
    # the supervisor thread (pre-pool behavior)
    replica_rebuild_workers: int = 1
    # SSE liveness: emit a comment keepalive when no event has been
    # written for this long (a stalled decode otherwise looks identical
    # to a slow one from the client side); 0 disables
    sse_keepalive_s: float = 15.0
    # ---- multi-host worker tier (REPLICA_MODE=socket) ----
    # advertised remote workers "host:port,host:port" the router DIALS —
    # one replica per address (overrides REPLICAS); empty = spawn local
    # socket workers that self-register against the router's listener
    replica_workers: str = ""
    # shared secret for the versioned registration handshake. Spawned-
    # local mode generates a per-process random token when empty; the
    # dial-out mode (REPLICA_WORKERS) REQUIRES an explicit token set
    # identically on both sides (the worker was started elsewhere)
    socket_auth_token: str = ""
    # worker-registry listener bind (self-registering workers dial this;
    # bind a routable interface for workers on other hosts)
    socket_bind_host: str = "127.0.0.1"
    socket_bind_port: int = 0
    # transport-liveness budget: NO frames from a worker for this long
    # (status frames flow at ~100 ms) latches the typed partition death —
    # the socket analogue of proc.is_alive() going false
    socket_partition_timeout_s: float = 2.0
    # frame codec bounds: an oversized frame is refused typed on both
    # sides; a partial frame (or a write the peer stopped draining) past
    # the timeout drops the connection instead of hanging a reader
    socket_frame_max_bytes: int = 32 * 1024 * 1024
    socket_frame_timeout_s: float = 30.0
    # rebuild grace in which a live, link-partitioned worker may
    # re-register (HEAL — keeps the process and its warm engine) before
    # the supervisor reaps and respawns
    socket_heal_grace_s: float = 5.0
    # fleet telemetry plane: cadence at which a process/socket worker ships
    # its metrics-registry deltas + duty snapshot over the RPC link as
    # low-priority `telemetry` frames (0 disables — the RPC hot path is
    # then byte-identical to the pre-telemetry protocol)
    telemetry_interval_s: float = 1.0
    # elastic fleet: duty-cycle autoscaler (inert by default — the
    # registry still accepts elastic joins/deregisters either way; these
    # knobs only govern the policy loop that ACTS on the load signal)
    autoscale: bool = False
    autoscale_min_replicas: int = 1
    autoscale_max_replicas: int = 4
    autoscale_window_s: float = 15.0
    autoscale_out_busy: float = 0.75
    autoscale_in_busy: float = 0.15
    autoscale_out_backlog: float = 0.5
    autoscale_out_cooldown_s: float = 30.0
    autoscale_in_cooldown_s: float = 60.0
    autoscale_poll_interval_s: float = 1.0

    @classmethod
    def from_env(cls) -> "ServeConfig":
        return cls(
            host=_env_str(["SENTIO_HOST", "API_HOST", "HOST"], "0.0.0.0"),
            port=_env_int(["SENTIO_PORT", "API_PORT", "PORT"], 8000),
            rate_limit_embed_per_min=_env_int(
                ["RATE_LIMIT_EMBED_PER_MIN", "RATE_LIMIT_EMBED"], 10
            ),
            rate_limit_default_per_min=_env_int(
                ["RATE_LIMIT_DEFAULT_PER_MIN", "RATE_LIMIT_DEFAULT"], 100
            ),
            max_question_chars=_env_int(["MAX_QUESTION_CHARS"], 2000),
            max_embed_chars=_env_int(["MAX_EMBED_CHARS"], 50_000),
            top_k_max=_env_int(["TOP_K_MAX"], 20),
            cors_origins=_env_str(["CORS_ORIGINS"], "*"),
            trust_proxy_headers=_env_bool(["TRUST_PROXY_HEADERS"], False),
            batch_deadline_ms=_env_float(["BATCH_DEADLINE_MS"], 8.0),
            batch_max_size=_env_int(["BATCH_MAX_SIZE"], 8),
            max_upload_mb=_env_int(["MAX_UPLOAD_MB"], 32),
            default_deadline_ms=_env_float(["DEADLINE_MS", "DEFAULT_DEADLINE_MS"], 0.0),
            admission_max_queue=_env_int(["ADMISSION_MAX_QUEUE"], 0),
            crash_retry_budget=_env_int(["CRASH_RETRY_BUDGET"], 1),
            drain_deadline_s=_env_float(["DRAIN_DEADLINE_S"], 10.0),
            replicas=_env_int(["REPLICAS", "SENTIO_REPLICAS"], 1),
            replica_mode=_env_str(["REPLICA_MODE"], "thread").strip().lower(),
            affinity_stickiness=_env_float(["AFFINITY_STICKINESS"], 4.0),
            route_prefix_tokens=_env_int(["ROUTE_PREFIX_TOKENS"], 512),
            tenant_weights=_env_str(["TENANT_WEIGHTS"], ""),
            tenant_default_weight=_env_float(["TENANT_DEFAULT_WEIGHT"], 1.0),
            tenant_refill_tokens_per_s=_env_float(
                ["TENANT_REFILL_TOKENS_PER_S"], 0.0
            ),
            tenant_burst_tokens=_env_int(["TENANT_BURST_TOKENS"], 8192),
            tenant_headroom=_env_int(["TENANT_HEADROOM"], -1),
            batch_shed_fraction=_env_float(["BATCH_SHED_FRACTION"], 0.8),
            replica_supervise=_env_bool(["REPLICA_SUPERVISE"], True),
            replica_probe_interval_s=_env_float(
                ["REPLICA_PROBE_INTERVAL_S"], 0.25
            ),
            replica_breaker_window_s=_env_float(
                ["REPLICA_BREAKER_WINDOW_S"], 30.0
            ),
            replica_breaker_error_rate=_env_float(
                ["REPLICA_BREAKER_ERROR_RATE"], 0.5
            ),
            replica_breaker_min_samples=_env_int(
                ["REPLICA_BREAKER_MIN_SAMPLES"], 4
            ),
            replica_breaker_tick_failures=_env_int(
                ["REPLICA_BREAKER_TICK_FAILURES"], 3
            ),
            replica_quarantine_backoff_s=_env_float(
                ["REPLICA_QUARANTINE_BACKOFF_S"], 0.5
            ),
            replica_rebuild_budget=_env_int(["REPLICA_REBUILD_BUDGET"], 3),
            replica_rebuild_drain_s=_env_float(
                ["REPLICA_REBUILD_DRAIN_S"], 5.0
            ),
            replica_failover_budget=_env_int(
                ["REPLICA_FAILOVER_BUDGET"], 1
            ),
            stream_resume_budget=_env_int(["STREAM_RESUME_BUDGET"], -1),
            tick_stall_budget_s=_env_float(["TICK_STALL_BUDGET_S"], 120.0),
            warmup_budget_s=_env_float(["WARMUP_BUDGET_S"], 600.0),
            replica_rebuild_workers=_env_int(
                ["REPLICA_REBUILD_WORKERS"], 1
            ),
            sse_keepalive_s=_env_float(["SSE_KEEPALIVE_S"], 15.0),
            replica_workers=_env_str(["REPLICA_WORKERS"], ""),
            socket_auth_token=_env_str(["SOCKET_AUTH_TOKEN"], ""),
            socket_bind_host=_env_str(["SOCKET_BIND_HOST"], "127.0.0.1"),
            socket_bind_port=_env_int(["SOCKET_BIND_PORT"], 0),
            socket_partition_timeout_s=_env_float(
                ["SOCKET_PARTITION_TIMEOUT_S"], 2.0
            ),
            socket_frame_max_bytes=_env_int(
                ["SOCKET_FRAME_MAX_BYTES"], 32 * 1024 * 1024
            ),
            socket_frame_timeout_s=_env_float(
                ["SOCKET_FRAME_TIMEOUT_S"], 30.0
            ),
            socket_heal_grace_s=_env_float(["SOCKET_HEAL_GRACE_S"], 5.0),
            telemetry_interval_s=_env_float(["TELEMETRY_INTERVAL_S"], 1.0),
            autoscale=_env_bool(["AUTOSCALE"], False),
            autoscale_min_replicas=_env_int(["AUTOSCALE_MIN_REPLICAS"], 1),
            autoscale_max_replicas=_env_int(["AUTOSCALE_MAX_REPLICAS"], 4),
            autoscale_window_s=_env_float(["AUTOSCALE_WINDOW_S"], 15.0),
            autoscale_out_busy=_env_float(["AUTOSCALE_OUT_BUSY"], 0.75),
            autoscale_in_busy=_env_float(["AUTOSCALE_IN_BUSY"], 0.15),
            autoscale_out_backlog=_env_float(
                ["AUTOSCALE_OUT_BACKLOG"], 0.5
            ),
            autoscale_out_cooldown_s=_env_float(
                ["AUTOSCALE_OUT_COOLDOWN_S"], 30.0
            ),
            autoscale_in_cooldown_s=_env_float(
                ["AUTOSCALE_IN_COOLDOWN_S"], 60.0
            ),
            autoscale_poll_interval_s=_env_float(
                ["AUTOSCALE_POLL_INTERVAL_S"], 1.0
            ),
        )

    def parsed_replica_workers(self) -> list[tuple[str, int]]:
        """``"hostA:9101,hostB:9101"`` → [("hostA", 9101), ...];
        malformed entries raise (a silently dropped worker address is a
        silently smaller serving tier)."""
        out: list[tuple[str, int]] = []
        for part in self.replica_workers.split(","):
            part = part.strip()
            if not part:
                continue
            host, sep, port = part.rpartition(":")
            if not sep or not host:
                raise ValueError(
                    f"REPLICA_WORKERS entry {part!r} is not host:port")
            out.append((host, int(port)))
        return out

    def parsed_tenant_weights(self) -> dict[str, float]:
        """``"a:4,b:1"`` → {"a": 4.0, "b": 1.0}; malformed entries skipped."""
        out: dict[str, float] = {}
        for part in self.tenant_weights.split(","):
            part = part.strip()
            if not part or ":" not in part:
                continue
            name, _, raw = part.partition(":")
            try:
                out[name.strip()] = float(raw)
            except ValueError:
                continue
        return out


@dataclass
class CacheConfig:
    """Cache tiers (reference: caching/cache_manager.py:18-125)."""

    backend: str = "memory"  # memory | multi_tier (L1 + redis L2) | off
    max_entries: int = 10_000
    default_ttl_s: float = 3600.0
    query_cache_ttl_s: float = 600.0
    redis_url: str = "redis://localhost:6379/0"
    redis_key_prefix: str = "sentio:"

    @classmethod
    def from_env(cls) -> "CacheConfig":
        return cls(
            backend=_env_str(["CACHE_BACKEND"], "memory"),
            max_entries=_env_int(["CACHE_MAX_ENTRIES"], 10_000),
            default_ttl_s=_env_float(["CACHE_TTL"], 3600.0),
            query_cache_ttl_s=_env_float(["QUERY_CACHE_TTL"], 600.0),
            redis_url=_env_str(["REDIS_URL"], "redis://localhost:6379/0"),
            redis_key_prefix=_env_str(["REDIS_KEY_PREFIX"], "sentio:"),
        )


@dataclass
class AuthConfig:
    """Auth/security (reference: utils/auth.py:30-77). Disabled by default in
    dev; JWT is stdlib HMAC-SHA256."""

    enabled: bool = False
    jwt_secret: str = ""
    access_ttl_s: int = 1800
    refresh_ttl_s: int = 7 * 24 * 3600
    max_failed_attempts: int = 5
    lockout_s: int = 900
    min_password_len: int = 12

    @classmethod
    def from_env(cls) -> "AuthConfig":
        return cls(
            enabled=_env_bool(["AUTH_ENABLED"], False),
            jwt_secret=_env_str(["JWT_SECRET", "JWT_SECRET_KEY"], ""),
            access_ttl_s=_env_int(["JWT_ACCESS_TTL"], 1800),
            refresh_ttl_s=_env_int(["JWT_REFRESH_TTL"], 7 * 24 * 3600),
            max_failed_attempts=_env_int(["AUTH_MAX_FAILED"], 5),
            lockout_s=_env_int(["AUTH_LOCKOUT_S"], 900),
            min_password_len=_env_int(["AUTH_MIN_PASSWORD_LEN"], 12),
        )


@dataclass
class ObservabilityConfig:
    """Tracing + metrics (reference: observability/tracing.py, metrics.py)."""

    tracing_enabled: bool = False
    otlp_endpoint: str = ""
    console_exporter: bool = False
    service_name: str = "sentio-tpu"
    metrics_enabled: bool = True
    monitor_interval_s: float = 30.0
    profiler_dir: str = ""  # non-empty => jax.profiler traces per batch step

    @classmethod
    def from_env(cls) -> "ObservabilityConfig":
        return cls(
            tracing_enabled=_env_bool(["TRACING_ENABLED", "OTEL_ENABLED"], False),
            otlp_endpoint=_env_str(["OTEL_EXPORTER_OTLP_ENDPOINT"], ""),
            console_exporter=_env_bool(["OTEL_CONSOLE"], False),
            service_name=_env_str(["OTEL_SERVICE_NAME"], "sentio-tpu"),
            metrics_enabled=_env_bool(["METRICS_ENABLED"], True),
            monitor_interval_s=_env_float(["MONITOR_INTERVAL_S"], 30.0),
            profiler_dir=_env_str(["JAX_PROFILER_DIR"], ""),
        )


@dataclass
class Settings:
    """The whole tree. Build with :func:`Settings.from_env` once at startup;
    tests construct it directly with overrides (no env mutation needed)."""

    chunking: ChunkingConfig = field(default_factory=ChunkingConfig)
    retrieval: RetrievalConfig = field(default_factory=RetrievalConfig)
    rerank: RerankConfig = field(default_factory=RerankConfig)
    embedder: EmbedderConfig = field(default_factory=EmbedderConfig)
    generator: GeneratorConfig = field(default_factory=GeneratorConfig)
    mesh: MeshConfig = field(default_factory=MeshConfig)
    serve: ServeConfig = field(default_factory=ServeConfig)
    cache: CacheConfig = field(default_factory=CacheConfig)
    auth: AuthConfig = field(default_factory=AuthConfig)
    observability: ObservabilityConfig = field(default_factory=ObservabilityConfig)
    data_dir: str = ".sentio"

    @classmethod
    def from_env(cls) -> "Settings":
        return cls(
            chunking=ChunkingConfig.from_env(),
            retrieval=RetrievalConfig.from_env(),
            rerank=RerankConfig.from_env(),
            embedder=EmbedderConfig.from_env(),
            generator=GeneratorConfig.from_env(),
            mesh=MeshConfig.from_env(),
            serve=ServeConfig.from_env(),
            cache=CacheConfig.from_env(),
            auth=AuthConfig.from_env(),
            observability=ObservabilityConfig.from_env(),
            data_dir=_env_str(["SENTIO_DATA_DIR"], ".sentio"),
        )

    def with_overrides(self, **sections) -> "Settings":
        return replace(self, **sections)


_settings: Optional[Settings] = None


def get_settings() -> Settings:
    """Process-wide settings singleton, built lazily from the environment."""
    global _settings
    if _settings is None:
        _settings = Settings.from_env()
    return _settings


def set_settings(settings: Optional[Settings]) -> None:
    """Install (or clear, with None) the singleton — used by tests and serve
    startup to pin an explicit tree."""
    global _settings
    _settings = settings
