"""HTTP serving layer: aiohttp app, handlers, DI container, schemas.

Parity with /root/reference/src/api/ (app.py, handlers/) and
src/core/dependencies.py — see the module docstrings for the line-level map.
"""

from sentio_tpu.serve.dependencies import DependencyContainer, get_container, set_container

__all__ = ["DependencyContainer", "get_container", "set_container", "create_app", "run_server"]


def __getattr__(name):
    # lazy: importing the container shouldn't drag aiohttp in
    if name in ("create_app", "run_server"):
        from sentio_tpu.serve import app as _app

        return getattr(_app, name)
    raise AttributeError(name)
