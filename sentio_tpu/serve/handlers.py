"""Request handlers: chat (with the degradation ladder) and health.

Parity with /root/reference/src/api/handlers/chat.py:25-274 and
health.py:20-344: the chat handler builds pipeline state with per-request
``user_top_k``/temperature metadata, invokes the graph, serializes cited
sources, and on ANY failure walks the 3-tier ladder — cached response →
template fallback → apology — so the endpoint never 500s on pipeline
errors. The health handler runs component probes concurrently with an
overall timeout and caches results for 10 s. TPU additions: device health
(mesh, HBM headroom) rides the detailed report.
"""

from __future__ import annotations

import asyncio
import logging
import time
import uuid
from typing import Any, Optional

from sentio_tpu.graph.state import create_initial_state

logger = logging.getLogger(__name__)

__all__ = ["ChatHandler", "HealthHandler"]


class ChatHandler:
    """Graph-invoking chat processor with soft-fail semantics."""

    def __init__(self, container) -> None:
        self.container = container
        self.settings = container.settings
        self._fallback = None

    @property
    def fallback(self):
        if self._fallback is None:
            from sentio_tpu.infra.resilience import FallbackResponseCache, LLMFallback

            self._fallback = (FallbackResponseCache(), LLMFallback())
        return self._fallback

    # ----------------------------------------------------------------- sync

    def process_chat_request_sync(
        self,
        question: str,
        top_k: Optional[int] = None,
        temperature: Optional[float] = None,
        mode: str = "balanced",
        thread_id: Optional[str] = None,
    ) -> dict[str, Any]:
        t0 = time.perf_counter()
        query_id = thread_id or uuid.uuid4().hex[:12]
        metadata: dict[str, Any] = {"query_id": query_id, "mode": mode}
        if top_k is not None:
            metadata["user_top_k"] = top_k
        if temperature is not None:
            metadata["temperature"] = temperature

        cache = self.container.cache_manager
        try:
            state = self.container.graph.invoke(
                create_initial_state(question, metadata=metadata),
                config={"thread_id": query_id},
            )
            answer = state.get("response", "")
            if not answer:
                raise RuntimeError("pipeline produced an empty response")
            result = {
                "answer": answer,
                "sources": self._serialize_sources(state),
                "metadata": {
                    **state.get("metadata", {}),
                    "query_id": query_id,
                    "latency_ms": round((time.perf_counter() - t0) * 1000.0, 1),
                    "degraded": False,
                },
            }
            if state.get("evaluation"):
                result["metadata"]["evaluation"] = state["evaluation"]
            cache.set_query_response(question, result)
            disk_cache, _ = self.fallback
            disk_cache.put(question, answer)
            return result
        except Exception as exc:  # noqa: BLE001 — ladder, never a 500
            logger.warning("chat pipeline failed (%s); degrading", exc)
            return self._degraded_response(question, query_id, str(exc), t0)

    def _degraded_response(
        self, question: str, query_id: str, error: str, t0: float
    ) -> dict[str, Any]:
        """cached → template → apology (reference chat.py:195-239 there)."""
        meta = {
            "query_id": query_id,
            "degraded": True,
            "error": error,
            "latency_ms": round((time.perf_counter() - t0) * 1000.0, 1),
        }
        cached = self.container.cache_manager.get_query_response(question)
        if cached and cached.get("answer"):
            return {**cached, "metadata": {**cached.get("metadata", {}), **meta, "tier": "query_cache"}}
        disk_cache, llm_fallback = self.fallback
        disk_hit = disk_cache.get(question)
        if disk_hit:
            return {"answer": disk_hit, "sources": [], "metadata": {**meta, "tier": "disk_cache"}}
        template = llm_fallback.no_llm(question)
        if template:
            return {"answer": template, "sources": [], "metadata": {**meta, "tier": "template"}}
        return {"answer": llm_fallback.apology(), "sources": [], "metadata": {**meta, "tier": "apology"}}

    @staticmethod
    def _serialize_sources(state: dict) -> list[dict[str, Any]]:
        """Cited sources from the best doc set (reference chat.py:158-166)."""
        from sentio_tpu.graph.state import best_documents

        out = []
        for doc in best_documents(state):
            out.append(
                {
                    "id": doc.id,
                    "text": doc.text[:500],
                    "score": doc.score(),
                    "metadata": {
                        k: v for k, v in doc.metadata.items()
                        if k in ("source", "filename", "score", "hybrid_score", "rerank_score")
                    },
                }
            )
        return out

    def stream_chat_sync(
        self,
        question: str,
        top_k: Optional[int] = None,
        temperature: Optional[float] = None,
        mode: str = "balanced",
    ):
        """Typed-event generator for SSE, with FULL graph-stage parity
        (reference factory.py:191-208 — streaming traverses the same graph):
        retrieve → rerank → select (dedup + token budget) → stream decode →
        verify. Yields ("sources", [...]) once, ("token", str) per increment,
        and ("verdict", {...}) after the stream when the verifier is on.
        Failures degrade to the ladder text instead of raw errors."""
        try:
            docs = self.container.retriever.retrieve(
                question, top_k=top_k or self.settings.retrieval.top_k
            )
            reranker = self.container.reranker
            if reranker is not None and docs:
                docs = reranker.rerank(
                    question, docs, top_k=self.settings.rerank.top_k
                ).documents
            from sentio_tpu.graph.nodes import select_documents

            selected, _used = select_documents(
                list(docs), self.settings.generator.context_token_budget
            )
            yield ("sources", [
                {"id": d.id, "source": d.metadata.get("source", d.id),
                 "score": d.score()} for d in selected
            ])
            chunks: list[str] = []
            for piece in self.container.generator.stream(
                question, selected, mode=mode, temperature=temperature
            ):
                chunks.append(piece)
                yield ("token", piece)
            verifier = self.container.verifier
            answer = "".join(chunks)
            if verifier is not None and answer:
                result = verifier.verify(question, answer, selected)
                yield ("verdict", result.to_dict())
        except Exception as exc:  # noqa: BLE001 — ladder, never a raw error
            logger.warning("stream pipeline failed (%s); degrading", exc)
            result = self._degraded_response(question, "stream", str(exc), time.perf_counter())
            yield ("token", result["answer"])

    # ---------------------------------------------------------------- async

    async def process_chat_request(self, **kwargs) -> dict[str, Any]:
        """The pipeline is synchronous device dispatch; keep the event loop
        free by running it on a worker thread."""
        return await asyncio.to_thread(self.process_chat_request_sync, **kwargs)


class HealthHandler:
    """basic / detailed / ready / live with a 10 s result cache."""

    CACHE_TTL_S = 10.0
    PROBE_TIMEOUT_S = 30.0

    def __init__(self, container) -> None:
        self.container = container
        self._cached: Optional[dict[str, Any]] = None
        self._cached_at = 0.0
        self._lock = asyncio.Lock()

    def basic(self) -> dict[str, Any]:
        return {
            "status": "healthy",
            "service": "sentio-tpu",
            "uptime_s": round(time.time() - self.container.started_at, 1),
        }

    def live(self) -> dict[str, Any]:
        return {"status": "alive"}

    def ready(self) -> dict[str, Any]:
        """Readiness = the container finished eager init (mesh + weights)."""
        ready = self.container._initialized
        return {"status": "ready" if ready else "initializing", "ready": ready}

    async def detailed(self) -> dict[str, Any]:
        async with self._lock:
            now = time.time()
            if self._cached is not None and now - self._cached_at < self.CACHE_TTL_S:
                return {**self._cached, "cached": True}
            try:
                components = await asyncio.wait_for(
                    asyncio.to_thread(self.container.check_dependency_health),
                    timeout=self.PROBE_TIMEOUT_S,
                )
            except asyncio.TimeoutError:
                components = {"error": {"healthy": False, "error": "health probe timeout"}}
            components["breakers"] = self._breaker_states()
            healthy = all(
                c.get("healthy", True) for c in components.values() if isinstance(c, dict)
            )
            report = {
                **self.basic(),
                "status": "healthy" if healthy else "degraded",
                "components": components,
                "cached": False,
            }
            self._cached, self._cached_at = report, now
            return report

    @staticmethod
    def _breaker_states() -> dict[str, Any]:
        try:
            from sentio_tpu.infra.resilience import registered_breakers

            return {name: b.health() for name, b in registered_breakers().items()}
        except ImportError:
            return {}
