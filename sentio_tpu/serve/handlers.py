"""Request handlers: chat (with the degradation ladder) and health.

Parity with /root/reference/src/api/handlers/chat.py:25-274 and
health.py:20-344: the chat handler builds pipeline state with per-request
``user_top_k``/temperature metadata, invokes the graph, serializes cited
sources, and on ANY failure walks the 3-tier ladder — cached response →
template fallback → apology — so the endpoint never 500s on pipeline
errors. The health handler runs component probes concurrently with an
overall timeout and caches results for 10 s. TPU additions: device health
(mesh, HBM headroom) rides the detailed report.
"""

from __future__ import annotations

import asyncio
import logging
import time
import uuid
from typing import Any, Optional

from sentio_tpu.graph.state import create_initial_state

logger = logging.getLogger(__name__)

__all__ = ["ChatHandler", "HealthHandler"]


class ChatHandler:
    """Graph-invoking chat processor with soft-fail semantics."""

    def __init__(self, container) -> None:
        self.container = container
        self.settings = container.settings
        self._fallback = None

    @property
    def fallback(self):
        if self._fallback is None:
            from sentio_tpu.infra.resilience import FallbackResponseCache, LLMFallback

            self._fallback = (FallbackResponseCache(), LLMFallback())
        return self._fallback

    # ----------------------------------------------------------------- sync

    def process_chat_request_sync(
        self,
        question: str,
        top_k: Optional[int] = None,
        temperature: Optional[float] = None,
        mode: str = "balanced",
        thread_id: Optional[str] = None,
        deadline_ts: Optional[float] = None,
        tenant: Optional[str] = None,
        priority: Optional[str] = None,
    ) -> dict[str, Any]:
        t0 = time.perf_counter()
        query_id = thread_id or uuid.uuid4().hex[:12]
        metadata: dict[str, Any] = {"query_id": query_id, "mode": mode}
        if top_k is not None:
            metadata["user_top_k"] = top_k
        if temperature is not None:
            metadata["temperature"] = temperature
        if deadline_ts is not None:
            # absolute perf_counter deadline rides metadata into the graph's
            # generate node and down into the decode-service ticket
            metadata["deadline_ts"] = deadline_ts
        if tenant is not None:
            # WFQ key: rides metadata into the generate node, whose decode
            # admission is charged to this tenant's fair-share quota
            metadata["tenant"] = tenant
        if priority is not None:
            metadata["priority"] = priority
        # flight record opens HERE — the query_id in metadata is the trace
        # context every downstream layer (graph executor, generator provider,
        # decode-engine pump) attaches its telemetry to
        from sentio_tpu.infra.flight import get_flight_recorder

        recorder = get_flight_recorder()
        recorder.start_request(
            query_id, endpoint="/chat", mode=mode, question_chars=len(question),
            **({"deadline_ms": round((deadline_ts - t0) * 1e3, 1)}
               if deadline_ts is not None else {}),
        )

        cache = self.container.cache_manager
        try:
            state = self.container.graph.invoke(
                create_initial_state(question, metadata=metadata),
                config={"thread_id": query_id},
            )
            answer = state.get("response", "")
            if not answer:
                raise RuntimeError("pipeline produced an empty response")
            # deadline_ts is a process-local perf_counter value — meaningless
            # (and misleading) outside this server; never serialize it to
            # clients or persist it into the query cache
            meta_out = {k: v for k, v in state.get("metadata", {}).items()
                        if k != "deadline_ts"}
            result = {
                "answer": answer,
                "sources": self._serialize_sources(state),
                "metadata": {
                    **meta_out,
                    "query_id": query_id,
                    "latency_ms": round((time.perf_counter() - t0) * 1000.0, 1),
                    "degraded": False,
                },
            }
            if state.get("evaluation"):
                result["metadata"]["evaluation"] = state["evaluation"]
            # NB: with VERIFY_MODE=async (or gated, below threshold) the
            # executor-stamped metadata.verify_pending flag rides meta_out
            # into the LIVE response — the answer ships NOW and the verdict
            # is fetchable at /debug/flight/{query_id} once it lands. The
            # CACHED copy must drop it: a cache replay serves a different
            # query_id with no detached verify behind it, so a baked-in
            # pending flag would promise a verdict that can never arrive.
            cache.set_query_response(question, {
                **result,
                "metadata": {k: v for k, v in result["metadata"].items()
                             if k != "verify_pending"},
            })
            disk_cache, _ = self.fallback
            disk_cache.put(question, answer)
            recorder.finish_request(
                query_id, status="done",
                latency_ms=result["metadata"]["latency_ms"],
            )
            return result
        except Exception as exc:  # noqa: BLE001 — ladder, never a 500
            if getattr(exc, "soft_fail_exempt", False):
                # typed shed / deadline errors skip the ladder: the caller
                # gets an honest 429/503/504 + Retry-After (mapped by the
                # serve error middleware) instead of a degraded 200
                recorder.finish_request(
                    query_id, status="shed", error=str(exc),
                    latency_ms=round((time.perf_counter() - t0) * 1000.0, 1),
                )
                raise
            logger.warning("chat pipeline failed (%s); degrading", exc)
            recorder.finish_request(
                query_id, status="degraded", error=str(exc),
                latency_ms=round((time.perf_counter() - t0) * 1000.0, 1),
            )
            return self._degraded_response(question, query_id, str(exc), t0)

    def _degraded_response(
        self, question: str, query_id: str, error: str, t0: float
    ) -> dict[str, Any]:
        """cached → template → apology (reference chat.py:195-239 there)."""
        meta = {
            "query_id": query_id,
            "degraded": True,
            "error": error,
            "latency_ms": round((time.perf_counter() - t0) * 1000.0, 1),
        }
        cached = self.container.cache_manager.get_query_response(question)
        if cached and cached.get("answer"):
            return {**cached, "metadata": {**cached.get("metadata", {}), **meta, "tier": "query_cache"}}
        disk_cache, llm_fallback = self.fallback
        disk_hit = disk_cache.get(question)
        if disk_hit:
            return {"answer": disk_hit, "sources": [], "metadata": {**meta, "tier": "disk_cache"}}
        template = llm_fallback.no_llm(question)
        if template:
            return {"answer": template, "sources": [], "metadata": {**meta, "tier": "template"}}
        return {"answer": llm_fallback.apology(), "sources": [], "metadata": {**meta, "tier": "apology"}}

    @staticmethod
    def _serialize_sources(state: dict) -> list[dict[str, Any]]:
        """Cited sources from the best doc set (reference chat.py:158-166)."""
        from sentio_tpu.graph.state import best_documents

        out = []
        for doc in best_documents(state):
            out.append(
                {
                    "id": doc.id,
                    "text": doc.text[:500],
                    "score": doc.score(),
                    "metadata": {
                        k: v for k, v in doc.metadata.items()
                        if k in ("source", "filename", "score", "hybrid_score", "rerank_score")
                    },
                }
            )
        return out

    def stream_chat_sync(
        self,
        question: str,
        top_k: Optional[int] = None,
        temperature: Optional[float] = None,
        mode: str = "balanced",
        request_id: Optional[str] = None,
        deadline_ts: Optional[float] = None,
        tenant: Optional[str] = None,
        priority: Optional[str] = None,
        resumable: bool = True,
    ):
        """Typed-event generator for SSE, with FULL graph-stage parity
        (reference factory.py:191-208 — streaming traverses the same graph):
        retrieve → rerank → select (dedup + token budget) → stream decode →
        verify. Yields ("sources", [...]) once, ("token", str) per increment,
        and ("verdict", {...}) after the stream when the verifier is on.
        Failures degrade to the ladder text instead of raw errors. The
        ``request_id`` opens a flight record whose stage timings mirror the
        stream's stages (streams bypass the graph executor, so the stages
        are timed here)."""
        from sentio_tpu.infra.flight import get_flight_recorder

        recorder = get_flight_recorder()
        t0 = time.perf_counter()
        if request_id:
            recorder.start_request(
                request_id, endpoint="/chat?stream", mode=mode,
                question_chars=len(question),
                **({"deadline_ms": round((deadline_ts - t0) * 1e3, 1)}
                   if deadline_ts is not None else {}),
            )
        timings: dict[str, float] = {}
        # set once the ANSWER's flight record has been finished (async/gated
        # close it at [DONE] time): the disconnect/degrade handlers below
        # must not re-finish it — that would clobber the answer-latency
        # 'done' record with an audit-inclusive 'disconnected'/'degraded'
        record_closed = False
        try:
            t = time.perf_counter()
            docs = self.container.retriever.retrieve(
                question, top_k=top_k or self.settings.retrieval.top_k
            )
            timings["retrieve"] = round((time.perf_counter() - t) * 1e3, 3)
            reranker = self.container.reranker
            if reranker is not None and docs:
                t = time.perf_counter()
                docs = reranker.rerank(
                    question, docs, top_k=self.settings.rerank.top_k
                ).documents
                timings["rerank"] = round((time.perf_counter() - t) * 1e3, 3)
            from sentio_tpu.graph.nodes import select_documents

            selected, _used = select_documents(
                list(docs), self.settings.generator.context_token_budget
            )
            yield ("sources", [
                {"id": d.id, "source": d.metadata.get("source", d.id),
                 "score": d.score()} for d in selected
            ])
            chunks: list[str] = []
            gen_stats: dict = {}
            t = time.perf_counter()
            for piece in self.container.generator.stream(
                question, selected, mode=mode, temperature=temperature,
                request_id=request_id, deadline_ts=deadline_ts,
                tenant=tenant, priority=priority, stats=gen_stats,
                resumable=resumable,
            ):
                chunks.append(piece)
                yield ("token", piece)
            timings["generate"] = round((time.perf_counter() - t) * 1e3, 3)
            verifier = self.container.verifier
            answer = "".join(chunks)
            # same deadline discipline as the graph verify node: skip the
            # optional audit when the budget is spent, and bound its decode
            # with the caller's deadline so the pump can cancel it
            deadline_ok = (deadline_ts is None
                           or time.perf_counter() < deadline_ts)
            verify_mode = self.settings.generator.verify_mode
            if verifier is not None and answer and deadline_ok:
                from sentio_tpu.graph.nodes import _record_verify
                from sentio_tpu.ops.confidence import confidence_score

                conf = None
                confident = False
                if verify_mode == "gated":
                    conf = confidence_score(
                        gen_stats.get("logprob_mean"),
                        gen_stats.get("logprob_min"), selected,
                    )
                    threshold = (
                        self.settings.generator.verify_confidence_threshold
                    )
                    confident = conf is not None and conf >= threshold
                if confident:
                    # gate pays off: typed skipped verdict, zero audit
                    # decode — same verdict shape as the graph gate node
                    from sentio_tpu.graph.nodes import (
                        confidence_skip_evaluation,
                    )

                    _record_verify(request_id, "gated", "skipped_confident",
                                   confidence=conf, skipped="confident")
                    yield ("verdict", confidence_skip_evaluation(conf))
                elif verify_mode in ("async", "gated"):
                    # answer first: the client gets [DONE] NOW and the
                    # flight record closes at ANSWER latency; the audit
                    # decodes while the connection idles (keepalives keep
                    # it warm) and the verdict trails as a `verify` event
                    yield ("done", "")
                    if request_id:
                        recorder.add_node_timings(request_id, timings)
                        recorder.finish_request(
                            request_id, status="done",
                            latency_ms=round(
                                (time.perf_counter() - t0) * 1e3, 1),
                        )
                    record_closed = True
                    # past this point the answer is DELIVERED and its
                    # record closed: a trailing-audit failure must degrade
                    # to a warn verdict, never to the apology ladder (which
                    # would append prose after [DONE]) and never touch the
                    # finished record (the verifier itself soft-fails to
                    # warn; this guards the telemetry around it too)
                    try:
                        t = time.perf_counter()
                        result = verifier.verify(question, answer, selected,
                                                 request_id=request_id,
                                                 deadline_ts=deadline_ts)
                        verdict_ms = round((time.perf_counter() - t) * 1e3, 3)
                        if request_id:
                            recorder.add_node_timings(
                                request_id, {"verify": verdict_ms})
                        _record_verify(request_id, verify_mode,
                                       result.verdict, confidence=conf,
                                       verdict_ms=verdict_ms)
                        trailing = result.to_dict()
                    except Exception as exc:  # noqa: BLE001
                        logger.warning("trailing verify failed (%s)", exc)
                        trailing = {"verdict": "warn", "citations_ok": True,
                                    "notes": [f"verify failed: {exc}"]}
                    if conf is not None:
                        trailing["confidence"] = round(conf, 4)
                    yield ("verify", trailing)
                    return
                else:
                    t = time.perf_counter()
                    result = verifier.verify(question, answer, selected,
                                             request_id=request_id,
                                             deadline_ts=deadline_ts)
                    verdict_ms = round((time.perf_counter() - t) * 1e3, 3)
                    timings["verify"] = verdict_ms
                    _record_verify(request_id, "sync", result.verdict,
                                   verdict_ms=verdict_ms)
                    yield ("verdict", result.to_dict())
            if request_id:
                recorder.add_node_timings(request_id, timings)
                recorder.finish_request(
                    request_id, status="done",
                    latency_ms=round((time.perf_counter() - t0) * 1e3, 1),
                )
        except GeneratorExit:
            # client disconnected mid-stream and the SSE pump closed this
            # generator — close the flight record (it would otherwise sit
            # status='active' until LRU eviction, making disconnect-heavy
            # traffic look like a pile of stuck requests in /debug/flight).
            # A disconnect AFTER the answer finished (e.g. an async-mode
            # client that closes on [DONE] while the trailing verdict is
            # still decoding) keeps the 'done' record: the answer WAS
            # delivered at the recorded latency.
            if request_id and not record_closed:
                recorder.add_node_timings(request_id, timings)
                recorder.finish_request(
                    request_id, status="disconnected",
                    latency_ms=round((time.perf_counter() - t0) * 1e3, 1),
                )
            raise
        except Exception as exc:  # noqa: BLE001 — ladder, never a raw error
            if record_closed:
                # answer already delivered and its record closed: nothing
                # left to degrade — surface nothing after [DONE]
                logger.warning("post-answer stream stage failed (%s)", exc)
                return
            if getattr(exc, "soft_fail_exempt", False):
                # shed / expired mid-stream: the SSE status is already on
                # the wire, so no 429/503 — but appending an apology after
                # real tokens would corrupt the answer, and ending with a
                # bare [DONE] would be indistinguishable from a successful
                # empty answer. Emit a typed error event, then end.
                if request_id:
                    recorder.add_node_timings(request_id, timings)
                    recorder.finish_request(
                        request_id, status="shed", error=str(exc),
                        latency_ms=round((time.perf_counter() - t0) * 1e3, 1),
                    )
                code = getattr(exc, "code", None)
                yield ("error", {
                    "code": getattr(code, "value", "OVERLOADED"),
                    "message": str(exc),
                    "retryable": bool(getattr(exc, "retryable", True)),
                })
                return
            logger.warning("stream pipeline failed (%s); degrading", exc)
            if request_id:
                recorder.add_node_timings(request_id, timings)
                recorder.finish_request(
                    request_id, status="degraded", error=str(exc),
                    latency_ms=round((time.perf_counter() - t0) * 1e3, 1),
                )
            result = self._degraded_response(question, "stream", str(exc), time.perf_counter())
            yield ("token", result["answer"])

    # ---------------------------------------------------------------- async

    async def process_chat_request(self, **kwargs) -> dict[str, Any]:
        """The pipeline is synchronous device dispatch; keep the event loop
        free by running it on a worker thread."""
        return await asyncio.to_thread(self.process_chat_request_sync, **kwargs)


class HealthHandler:
    """basic / detailed / ready / live with a 10 s result cache."""

    CACHE_TTL_S = 10.0
    PROBE_TIMEOUT_S = 30.0

    def __init__(self, container) -> None:
        self.container = container
        self._cached: Optional[dict[str, Any]] = None
        self._cached_at = 0.0
        self._lock = asyncio.Lock()

    def basic(self) -> dict[str, Any]:
        """Cheap liveness-with-capacity view: replica failure domains fold
        in here. ``degraded`` means ready at reduced capacity (1 ≤ serving
        replicas < N — k8s must KEEP routing to this pod while the
        supervisor rebuilds the dead replica in place); ``unhealthy`` only
        when zero replicas can serve, the one state where restarting the
        pod beats waiting."""
        out = {
            "status": "healthy",
            "service": "sentio-tpu",
            "uptime_s": round(time.perf_counter() - self.container.started_at, 1),
        }
        service = self.container.peek("generation_service")
        if service is not None and hasattr(service, "health_summary"):
            try:
                replicas = service.health_summary()
            except Exception:  # noqa: BLE001 — health must never 500
                logger.debug("replica health summary failed", exc_info=True)
            else:
                out["status"] = replicas["status"]
                out["replicas"] = {
                    k: replicas[k]
                    for k in ("healthy_replicas", "serving_replicas",
                              "total_replicas", "replicas")
                }
        return out

    def live(self) -> dict[str, Any]:
        return {"status": "alive"}

    def ready(self) -> dict[str, Any]:
        """Readiness = the container finished eager init (mesh + weights)."""
        ready = self.container._initialized
        return {"status": "ready" if ready else "initializing", "ready": ready}

    async def detailed(self) -> dict[str, Any]:
        async with self._lock:
            now = time.perf_counter()
            if self._cached is not None and now - self._cached_at < self.CACHE_TTL_S:
                return {**self._cached, "cached": True}
            try:
                components = await asyncio.wait_for(
                    asyncio.to_thread(self.container.check_dependency_health),
                    timeout=self.PROBE_TIMEOUT_S,
                )
            except asyncio.TimeoutError:
                components = {"error": {"healthy": False, "error": "health probe timeout"}}
            components["breakers"] = self._breaker_states()
            healthy = all(
                c.get("healthy", True) for c in components.values() if isinstance(c, dict)
            )
            report = {
                **self.basic(),
                "status": "healthy" if healthy else "degraded",
                "components": components,
                "cached": False,
            }
            self._cached, self._cached_at = report, now
            return report

    @staticmethod
    def _breaker_states() -> dict[str, Any]:
        try:
            from sentio_tpu.infra.resilience import registered_breakers

            return {name: b.health() for name, b in registered_breakers().items()}
        except ImportError:
            return {}
