"""Request/response schemas for the HTTP surface.

Parity with /root/reference/src/api/app.py:118-203 (``ChatRequest`` question
1-2000 chars / top_k 1-20 / temperature 0-2, ``EmbedRequest`` content
≤50 000 chars, typed response bodies). Validation is plain functions over
parsed JSON — same limits, explicit error lists, no framework coupling.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

from sentio_tpu.config import ServeConfig

__all__ = [
    "SchemaError", "ChatRequest", "EmbedRequest",
    "parse_chat_request", "parse_embed_request", "MAX_DEADLINE_MS",
]

# upper bound on a caller-supplied deadline (1 hour) — shared by the body
# field validation below and the X-Deadline-Ms header parse in serve/app.py
MAX_DEADLINE_MS = 3_600_000


class SchemaError(ValueError):
    """Carries per-field validation errors for a 422 response body."""

    def __init__(self, errors: list[dict[str, str]]):
        super().__init__("; ".join(f"{e['field']}: {e['error']}" for e in errors))
        self.errors = errors


@dataclass
class ChatRequest:
    question: str
    top_k: Optional[int] = None
    temperature: Optional[float] = None
    mode: str = "balanced"
    thread_id: Optional[str] = None
    stream: bool = False
    # caller's total latency budget in ms (body field; the X-Deadline-Ms
    # header and the serve default fill it when absent) — the decode service
    # sheds/cancels work that cannot finish inside it
    deadline_ms: Optional[float] = None
    # streaming session continuity opt-out (body field; the X-Resumable
    # header fills it when absent): false = a replica dying mid-stream
    # surfaces the typed mid-stream error instead of resuming the
    # delivered prefix on a survivor. None = server default (resume).
    resumable: Optional[bool] = None


@dataclass
class EmbedRequest:
    content: str
    metadata: dict[str, Any] = field(default_factory=dict)


def _require_dict(body: Any) -> dict:
    if not isinstance(body, dict):
        raise SchemaError([{"field": "body", "error": "expected a JSON object"}])
    return body


def parse_chat_request(body: Any, limits: ServeConfig) -> ChatRequest:
    body = _require_dict(body)
    errors: list[dict[str, str]] = []

    question = body.get("question", body.get("query"))
    if not isinstance(question, str) or not question.strip():
        errors.append({"field": "question", "error": "required non-empty string"})
        question = ""
    elif len(question) > limits.max_question_chars:
        errors.append(
            {"field": "question", "error": f"longer than {limits.max_question_chars} chars"}
        )

    top_k = body.get("top_k")
    if top_k is not None:
        if not isinstance(top_k, int) or isinstance(top_k, bool) or not (1 <= top_k <= limits.top_k_max):
            errors.append({"field": "top_k", "error": f"must be an int in [1, {limits.top_k_max}]"})
            top_k = None

    temperature = body.get("temperature")
    if temperature is not None:
        if not isinstance(temperature, (int, float)) or isinstance(temperature, bool) or not (
            0.0 <= float(temperature) <= 2.0
        ):
            errors.append({"field": "temperature", "error": "must be a number in [0, 2]"})
            temperature = None
        else:
            temperature = float(temperature)

    mode = body.get("mode", "balanced")
    if mode not in ("fast", "balanced", "quality", "creative"):
        errors.append({"field": "mode", "error": "one of fast|balanced|quality|creative"})
        mode = "balanced"

    thread_id = body.get("thread_id")
    if thread_id is not None and not isinstance(thread_id, str):
        errors.append({"field": "thread_id", "error": "must be a string"})
        thread_id = None

    resumable = body.get("resumable")
    if resumable is not None and not isinstance(resumable, bool):
        errors.append({"field": "resumable", "error": "must be a boolean"})
        resumable = None

    deadline_ms = body.get("deadline_ms")
    if deadline_ms is not None:
        if not isinstance(deadline_ms, (int, float)) or isinstance(deadline_ms, bool) or not (
            0 < float(deadline_ms) <= MAX_DEADLINE_MS
        ):
            errors.append({
                "field": "deadline_ms",
                "error": f"must be a number in (0, {MAX_DEADLINE_MS}]",
            })
            deadline_ms = None
        else:
            deadline_ms = float(deadline_ms)

    if errors:
        raise SchemaError(errors)
    return ChatRequest(
        question=question.strip(),
        top_k=top_k,
        temperature=temperature,
        mode=mode,
        thread_id=thread_id,
        stream=bool(body.get("stream", False)),
        deadline_ms=deadline_ms,
        resumable=resumable,
    )


def parse_embed_request(body: Any, limits: ServeConfig) -> EmbedRequest:
    body = _require_dict(body)
    errors: list[dict[str, str]] = []

    content = body.get("content", body.get("text"))
    if not isinstance(content, str) or not content.strip():
        errors.append({"field": "content", "error": "required non-empty string"})
        content = ""
    elif len(content) > limits.max_embed_chars:
        errors.append({"field": "content", "error": f"longer than {limits.max_embed_chars} chars"})

    metadata = body.get("metadata") or {}
    if not isinstance(metadata, dict):
        errors.append({"field": "metadata", "error": "must be an object"})
        metadata = {}

    if errors:
        raise SchemaError(errors)
    return EmbedRequest(content=content, metadata=metadata)
