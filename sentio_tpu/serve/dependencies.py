"""DependencyContainer — lazy singletons for every serving component.

Parity with /root/reference/src/core/dependencies.py:24-392 (lazy component
properties, ordered ``initialize_all`` under a lock, ``cleanup``, module
singleton + accessors, ``check_dependency_health``) with the TPU-critical
inversion (SURVEY.md §3.3): the expensive state — device mesh, model
weights, corpus embeddings in HBM — is built ONCE at startup by
``initialize_all``, so the first ``/chat`` pays no model cold start. The
reference instead lazily builds its graph (and scrolls the whole Qdrant
corpus) on the first request (chat.py:38-87 there).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Optional

from sentio_tpu.config import Settings, get_settings

logger = logging.getLogger(__name__)

__all__ = ["DependencyContainer", "get_container", "set_container"]


class DependencyContainer:
    """Every component is a lazy cached property; ``initialize_all`` forces
    construction in dependency order. Tests inject fakes via the
    constructor-style ``overrides`` mapping (the reference's
    ``dependency_overrides`` pattern, conftest there)."""

    def __init__(self, settings: Optional[Settings] = None, **overrides: Any) -> None:
        self.settings = settings or get_settings()
        self._cache: dict[str, Any] = dict(overrides)
        self._lock = threading.RLock()
        self._initialized = False
        self.started_at = time.perf_counter()

    def _get(self, name: str, build) -> Any:
        with self._lock:
            if name not in self._cache:
                self._cache[name] = build()
            return self._cache[name]

    def override(self, name: str, value: Any) -> None:
        with self._lock:
            self._cache[name] = value

    def peek(self, name: str) -> Any:
        """Already-built component or None — never constructs AND never
        blocks: initialize_all holds the container lock for the whole eager
        startup (weights onto HBM, potentially minutes), and a /metrics
        scrape waiting on it would freeze the event loop — liveness probes
        included. A plain dict read is GIL-atomic."""
        return self._cache.get(name)

    # ------------------------------------------------------------ components

    @property
    def mesh(self):
        def build():
            cfg = self.settings.mesh
            if cfg.dp_size == 0 and cfg.tp_size <= 1 and cfg.sp_size <= 1:
                import jax

                if len(jax.devices()) <= 1:
                    return None  # single chip: skip mesh machinery entirely
            from sentio_tpu.parallel.mesh import build_mesh

            return build_mesh(cfg)

        return self._get("mesh", build)

    @property
    def embedder(self):
        def build():
            from sentio_tpu.ops.embedder import get_embedder

            return get_embedder(self.settings.embedder, mesh=self.mesh)

        return self._get("embedder", build)

    @property
    def dense_index(self):
        def build():
            from pathlib import Path

            from sentio_tpu.ops.dense_index import TpuDenseIndex

            cfg = self.settings.retrieval
            if cfg.index_backend != "tpu":
                # external-store escape hatch (SURVEY.md §7: corpora too
                # large for in-HBM exact search) — one construction path,
                # the registry, so config wiring can't drift
                from sentio_tpu.ops.vector_store import get_vector_store

                return get_vector_store(
                    cfg.index_backend,
                    dim=self.embedder.dimension,
                    mesh=self.mesh,
                    settings=self.settings,
                )
            path = self.settings.retrieval.index_path
            # save() writes <path>.npz + <path>.json — check the metadata file
            if path and Path(path).with_suffix(".json").exists():
                logger.info("loading dense index from %s", path)
                index = TpuDenseIndex.load(
                    path, mesh=self.mesh, dtype=self.settings.generator.dtype
                )
                want = self.embedder.dimension
                if index.dim != want:
                    raise ValueError(
                        f"persisted dense index at {path} has dim={index.dim} but the "
                        f"configured embedder produces dim={want} — re-ingest with the "
                        "current embedder or point SENTIO_INDEX_PATH elsewhere"
                    )
                return index
            return TpuDenseIndex(
                dim=self.embedder.dimension,
                mesh=self.mesh,
                dtype=self.settings.generator.dtype,
            )

        return self._get("dense_index", build)

    @property
    def sparse_index(self):
        def build():
            from sentio_tpu.ops.bm25 import BM25Params, make_bm25_index

            cfg = self.settings.retrieval
            index = make_bm25_index(
                params=BM25Params(k1=cfg.bm25_k1, b=cfg.bm25_b),
                backend=cfg.bm25_backend,
            )
            docs = self.dense_index.documents()
            if docs:  # rehydrate from a persisted dense index
                index.build(docs)
            return index

        return self._get("sparse_index", build)

    @property
    def web_cache_index(self):
        """Persisted cached-web-results collection, consulted by the hybrid
        retriever before fusion (reference's `web_cache` Qdrant collection,
        hybrid.py:96-107 there). None unless a persisted index exists."""

        def build():
            from pathlib import Path

            from sentio_tpu.ops.dense_index import TpuDenseIndex

            path = self.settings.retrieval.web_cache_path
            if not path or not Path(path).with_suffix(".json").exists():
                return None
            logger.info("loading web-cache index from %s", path)
            return TpuDenseIndex.load(
                path, mesh=self.mesh, dtype=self.settings.generator.dtype
            )

        return self._get("web_cache_index", build)

    @property
    def retriever(self):
        def build():
            from sentio_tpu.ops.retrievers import create_retriever

            return create_retriever(
                settings=self.settings,
                embedder=self.embedder,
                dense_index=self.dense_index,
                bm25_index=self.sparse_index,
                web_cache_index=self.web_cache_index,
            )

        return self._get("retriever", build)

    @property
    def reranker(self):
        def build():
            if not self.settings.rerank.enabled:
                return None
            from sentio_tpu.ops.reranker import get_reranker

            return get_reranker(self.settings.rerank.kind, config=self.settings.rerank, mesh=self.mesh)

        return self._get("reranker", build)

    @property
    def engine(self):
        def build():
            cfg = self.settings.generator
            if cfg.provider != "tpu":
                return None
            from sentio_tpu.models.llama import LlamaConfig
            from sentio_tpu.runtime.engine import GeneratorEngine

            model_cfg = LlamaConfig.tiny() if cfg.model_preset == "tiny" else None
            return GeneratorEngine(config=cfg, model_config=model_cfg, mesh=self.mesh)

        return self._get("engine", build)

    @property
    def speculative(self):
        """Draft-accelerated decoder over the contiguous engine
        (runtime/speculative.py) — built when a draft checkpoint is
        configured. Greedy calls are bit-exact and sampled calls
        distribution-exact, so it transparently serves all non-paged
        requests."""

        def build():
            cfg = self.settings.generator
            if cfg.provider != "tpu" or not cfg.draft_checkpoint_path:
                return None
            if cfg.use_paged_decode:
                # the PAGED engine itself speculates now (generation_service
                # loads the draft into the continuous-batching tick) — the
                # contiguous SpeculativeDecoder would be dead weight here
                return None
            engine = self.engine
            if engine is None or self.mesh is not None:
                return None  # mesh-backed engines: spec not wired yet
            from sentio_tpu.runtime.speculative import SpeculativeDecoder
            from sentio_tpu.runtime.weights import load_model

            draft_params, draft_cfg, _ = load_model(cfg.draft_checkpoint_path)
            return SpeculativeDecoder(
                engine, draft_params, draft_cfg, k=cfg.speculative_k
            )

        return self._get("speculative", build)

    @property
    def generation_service(self):
        """Multi-replica continuous-batching tier over the paged KV pool —
        the default decode path for /chat. A :class:`ReplicaSet` owns
        REPLICAS independent engine+service replicas (private pool, radix
        tree, and pump each; weights/tokenizer shared with the contiguous
        engine, which keeps escape-hatch duty), routes by radix-prefix
        affinity then least-loaded, and applies per-tenant weighted fair
        queueing in front. REPLICAS=1 degenerates to the single-engine
        behavior every existing test pins."""

        def build():
            cfg = self.settings.generator
            serve = self.settings.serve
            if cfg.provider != "tpu" or not cfg.use_paged_decode:
                return None
            engine = self.engine
            if engine is None:
                return None
            from sentio_tpu.runtime.paged import ContinuousBatchingEngine
            from sentio_tpu.runtime.replica import ReplicaSet
            from sentio_tpu.runtime.service import PagedGenerationService

            n_replicas = max(serve.replicas, 1)
            replica_mode = serve.replica_mode
            if replica_mode not in ("thread", "process", "socket"):
                # a typo must not SILENTLY degrade to the GIL-bound thread
                # tier while the operator believes they have OS-level
                # failure domains
                logger.warning(
                    "REPLICA_MODE=%r unknown (expected "
                    "thread|process|socket); using thread mode",
                    replica_mode,
                )
                replica_mode = "thread"
            if replica_mode in ("process", "socket") and self.mesh is not None:
                # per-process replicas over dp-axis mesh slices need
                # coordinated multi-process device init — the remaining
                # ROADMAP item 1 leg. Fall back rather than half-work.
                logger.warning(
                    "REPLICA_MODE=%s ignored: a device mesh is "
                    "configured (MESH_* > 1) and mesh-sliced worker "
                    "replicas are not wired yet; using thread mode",
                    replica_mode,
                )
                replica_mode = "thread"

            # paged speculative decoding: a configured draft checkpoint now
            # accelerates the DEFAULT serving path (runtime/paged_spec.py)
            # instead of being dead under USE_PAGED_KV=1 (round-4 advisor)
            draft_params = draft_cfg = None
            if cfg.draft_checkpoint_path and self.mesh is not None:
                logger.warning(
                    "LLM_DRAFT_CHECKPOINT ignored: paged speculation does "
                    "not support a device mesh yet (MESH_* > 1 configured); "
                    "/info reports this under generator.speculative"
                )
            if cfg.draft_checkpoint_path and self.mesh is None:
                if cfg.prefill_chunk:
                    logger.warning(
                        "LLM_DRAFT_CHECKPOINT ignored: PREFILL_CHUNK is set "
                        "and paged speculation requires whole-prompt "
                        "admission (the draft prefills full prompts)"
                    )
                elif replica_mode in ("process", "socket"):
                    # workers load the draft themselves (mmap-shared, via
                    # WorkerSpec below) — loading a private router-process
                    # copy here would defeat the one-copy-per-host goal
                    logger.info(
                        "paged speculation: draft %s loads in-worker (k=%d)",
                        cfg.draft_checkpoint_path, cfg.speculative_k,
                    )
                else:
                    from sentio_tpu.runtime.weights import load_model

                    draft_params, draft_cfg, _ = load_model(
                        cfg.draft_checkpoint_path, expect_family="llama"
                    )
                    logger.info(
                        "paged speculation: draft %s (dim=%d L=%d, k=%d)",
                        cfg.draft_checkpoint_path, draft_cfg.dim,
                        draft_cfg.n_layers, cfg.speculative_k,
                    )
            # replicas map onto dp-axis slices of the mesh when it divides
            # evenly; otherwise every replica shares the whole mesh (their
            # dispatches serialize on device — still correct, no scale-out)
            meshes = [self.mesh] * n_replicas
            if self.mesh is not None and n_replicas > 1:
                from sentio_tpu.parallel.mesh import MeshError, split_mesh_dp

                try:
                    meshes = split_mesh_dp(self.mesh, n_replicas)
                    logger.info(
                        "replicas mapped onto %d dp-axis mesh slices",
                        n_replicas,
                    )
                except MeshError as exc:
                    logger.warning(
                        "REPLICAS=%d cannot slice the dp axis (%s); "
                        "replicas will share the whole mesh", n_replicas, exc,
                    )

            warm_head = ""
            if cfg.prefix_cache:
                # the radix cache learns shared heads automatically from
                # traffic; warming the rendered template head (instruction +
                # section header) just spares the FIRST /chat its cold
                # prefill of that span — per replica, since each owns a
                # private tree
                from sentio_tpu.ops.prompts import PromptBuilder

                prompts = PromptBuilder()
                warm_head = prompts.static_head(
                    "retrieve", instruction=prompts.load("profile")
                ) or ""

            if replica_mode in ("process", "socket"):
                # worker replica tier (runtime/worker.py): each replica is
                # a worker process owning its private engine+service+pump;
                # the router keeps only a thin RPC shim per replica.
                # Weights are NOT shipped through the transport — each
                # worker loads the checkpoint itself, memory-mapped, so N
                # workers share one page-cache copy per host (or re-derive
                # the identical seeded random init in the no-checkpoint
                # dev mode). "process" runs the spawn-pipe transport;
                # "socket" runs the TCP transport: spawned local workers
                # self-register against the router's WorkerRegistry
                # listener, or — with REPLICA_WORKERS=host:port,... — the
                # router dials workers already serving on OTHER hosts
                # (started there via runtime.worker.worker_serve) and the
                # supervisor's rebuild duck-types to re-dial/await
                # re-registration with backoff.
                import dataclasses as _dc

                from sentio_tpu.runtime.worker import (
                    ProcessReplica,
                    WorkerSpec,
                )

                engine_kwargs = dict(
                    max_slots=cfg.max_batch_size,
                    page_size=cfg.kv_page_size,
                    max_pages_per_seq=cfg.kv_max_pages_per_seq,
                    steps_per_tick=cfg.decode_steps_per_tick,
                    max_tick_steps=cfg.decode_max_tick_steps,
                    pipeline_depth=cfg.decode_pipeline_depth,
                    kv_quant=cfg.kv_quant,
                    prefill_chunk=cfg.prefill_chunk or None,
                    spec_k=cfg.speculative_k,
                    prefix_cache=cfg.prefix_cache,
                )
                service_kwargs = dict(
                    max_queue=serve.admission_max_queue or None,
                    default_deadline_s=(
                        serve.default_deadline_ms / 1e3
                        if serve.default_deadline_ms > 0 else None
                    ),
                    retry_budget=serve.crash_retry_budget,
                    tick_stall_budget_s=serve.tick_stall_budget_s,
                    warmup_budget_s=serve.warmup_budget_s,
                )
                draft_path = ""
                if cfg.draft_checkpoint_path and not cfg.prefill_chunk:
                    # the draft loads INSIDE each worker (mmap-shared);
                    # the prefill_chunk incompatibility warning above
                    # applies identically
                    draft_path = cfg.draft_checkpoint_path
                registry = None
                worker_addrs: list = []
                auth_token = ""
                if replica_mode == "socket":
                    import secrets as _secrets

                    from sentio_tpu.runtime.replica import WorkerRegistry

                    worker_addrs = serve.parsed_replica_workers()
                    if worker_addrs:
                        # advertised remote workers: one replica per
                        # address; both sides must share the explicit token
                        if not serve.socket_auth_token:
                            raise ValueError(
                                "REPLICA_WORKERS needs SOCKET_AUTH_TOKEN "
                                "set identically on router and workers"
                            )
                        n_replicas = len(worker_addrs)
                    auth_token = (serve.socket_auth_token
                                  or _secrets.token_hex(16))
                    registry = WorkerRegistry(
                        auth_token, slots=n_replicas,
                        bind_host=serve.socket_bind_host,
                        bind_port=serve.socket_bind_port,
                        max_frame_bytes=serve.socket_frame_max_bytes,
                        frame_timeout_s=serve.socket_frame_timeout_s,
                    )
                    self._cache["worker_registry"] = registry
                def make_spec(i: int) -> WorkerSpec:
                    # shared by the startup loop, the elastic-join
                    # membership source, and the autoscaler's launcher —
                    # one spec recipe, three registration paths
                    return WorkerSpec(factory_kwargs=dict(
                        model_family=(
                            "moe" if type(engine.model_config).__name__
                            == "MoeConfig" else "llama"
                        ),
                        model_config=(
                            None if cfg.checkpoint_path
                            else _dc.asdict(engine.model_config)
                        ),
                        checkpoint_path=cfg.checkpoint_path,
                        tokenizer_path=cfg.tokenizer_path,
                        draft_checkpoint_path=draft_path,
                        engine_kwargs=engine_kwargs,
                        service_kwargs={**service_kwargs,
                                        "replica_id": i},
                        warm_prefix_text=warm_head,
                    ), telemetry_interval_s=serve.telemetry_interval_s,
                       **({} if replica_mode != "socket" else dict(
                        auth_token=auth_token,
                        reconnect=True,
                        max_frame_bytes=serve.socket_frame_max_bytes,
                        frame_timeout_s=serve.socket_frame_timeout_s,
                    )))

                services = []
                try:
                    for i in range(n_replicas):
                        spec = make_spec(i)
                        transport_kwargs = (
                            {} if replica_mode != "socket" else dict(
                                transport_mode="socket",
                                registry=registry,
                                connect_addr=(worker_addrs[i]
                                              if worker_addrs else None),
                                partition_timeout_s=(
                                    serve.socket_partition_timeout_s),
                                heal_grace_s=serve.socket_heal_grace_s,
                            ))
                        services.append(ProcessReplica(
                            spec, engine.tokenizer, replica_id=i,
                            **transport_kwargs,
                        ))
                    logger.info(
                        "%s-mode replica tier: %d workers (pids %s%s)",
                        replica_mode, n_replicas,
                        [s.pid for s in services],
                        (f", registry {registry.address}" if registry
                         else ""),
                    )
                    replica_set = ReplicaSet(
                        services,
                        tenant_weights=serve.parsed_tenant_weights(),
                        tenant_default_weight=serve.tenant_default_weight,
                        tenant_refill_tokens_per_s=(
                            serve.tenant_refill_tokens_per_s
                        ),
                        tenant_burst_tokens=serve.tenant_burst_tokens,
                        tenant_headroom=(serve.tenant_headroom
                                         if serve.tenant_headroom >= 0
                                         else None),
                        batch_shed_fraction=serve.batch_shed_fraction,
                        affinity_stickiness=serve.affinity_stickiness,
                        route_prefix_tokens=serve.route_prefix_tokens,
                        supervise=serve.replica_supervise,
                        probe_interval_s=serve.replica_probe_interval_s,
                        breaker_window_s=serve.replica_breaker_window_s,
                        breaker_error_rate=serve.replica_breaker_error_rate,
                        breaker_min_samples=(
                            serve.replica_breaker_min_samples
                        ),
                        breaker_tick_failures=(
                            serve.replica_breaker_tick_failures
                        ),
                        quarantine_backoff_s=(
                            serve.replica_quarantine_backoff_s
                        ),
                        rebuild_budget=serve.replica_rebuild_budget,
                        rebuild_drain_s=serve.replica_rebuild_drain_s,
                        failover_budget=serve.replica_failover_budget,
                        stream_resume_budget=(
                            serve.stream_resume_budget
                            if serve.stream_resume_budget >= 0 else None
                        ),
                        rebuild_workers=serve.replica_rebuild_workers,
                    )
                except BaseException:
                    # a failed spawn — or a ReplicaSet constructor reject —
                    # must not leak the workers already running: each is a
                    # live OS process holding an engine + KV pool, and
                    # _get retries this build on the next request,
                    # multiplying the leak
                    for s in services:
                        try:
                            s.close(join_timeout_s=5.0)
                        except Exception:  # noqa: BLE001 — reap best-effort
                            pass
                    if registry is not None:
                        try:
                            registry.close()
                        except Exception:  # noqa: BLE001 — best-effort
                            pass
                        self._cache.pop("worker_registry", None)
                    raise
                if registry is not None:
                    # elastic fleet: workers that hello AFTER startup with
                    # the sentinel slot -1 land on the registry's join
                    # queue; the supervisor drains it through this source
                    # and wires each one into routing/WFQ/health. Active
                    # regardless of AUTOSCALE — remote fleets scale
                    # themselves by just registering.
                    def _join_elastic():
                        joined = []
                        for slot in registry.drain_joins():
                            svc = ProcessReplica(
                                make_spec(slot), engine.tokenizer,
                                replica_id=slot,
                                transport_mode="socket",
                                registry=registry,
                                adopt_registration=True,
                                partition_timeout_s=(
                                    serve.socket_partition_timeout_s),
                                heal_grace_s=serve.socket_heal_grace_s,
                            )
                            joined.append((slot, svc))
                        return joined

                    replica_set.set_membership_source(
                        _join_elastic, release_slot=registry.release_slot)
                if serve.autoscale:
                    from sentio_tpu.runtime.autoscaler import (
                        AutoscalePolicy, Autoscaler, socket_worker_launcher,
                    )

                    launcher = None
                    if registry is not None:
                        launcher = socket_worker_launcher(
                            registry.address, make_spec(-1))
                    autoscaler = Autoscaler(
                        replica_set,
                        AutoscalePolicy(
                            min_replicas=serve.autoscale_min_replicas,
                            max_replicas=serve.autoscale_max_replicas,
                            window_s=serve.autoscale_window_s,
                            out_busy=serve.autoscale_out_busy,
                            in_busy=serve.autoscale_in_busy,
                            out_backlog=serve.autoscale_out_backlog,
                            out_cooldown_s=serve.autoscale_out_cooldown_s,
                            in_cooldown_s=serve.autoscale_in_cooldown_s,
                        ),
                        launcher=launcher,
                        poll_interval_s=serve.autoscale_poll_interval_s,
                    )
                    autoscaler.start()
                    self._cache["autoscaler"] = autoscaler
                return replica_set

            services = []
            for i in range(n_replicas):
                paged = ContinuousBatchingEngine(
                    model_config=engine.model_config,
                    params=engine.params,
                    tokenizer=engine.tokenizer,
                    max_slots=cfg.max_batch_size,
                    page_size=cfg.kv_page_size,
                    max_pages_per_seq=cfg.kv_max_pages_per_seq,
                    steps_per_tick=cfg.decode_steps_per_tick,
                    max_tick_steps=cfg.decode_max_tick_steps,
                    pipeline_depth=cfg.decode_pipeline_depth,
                    kv_quant=cfg.kv_quant,
                    prefill_chunk=cfg.prefill_chunk or None,
                    draft_params=draft_params,
                    draft_config=draft_cfg,
                    spec_k=cfg.speculative_k,
                    prefix_cache=cfg.prefix_cache,
                    mesh=meshes[i],  # pool kv-heads shard over tp with the weights
                )
                if warm_head:
                    shared = paged.warm_prefix(warm_head)
                    if shared and i == 0:
                        logger.info(
                            "prefix cache warmed: %d tokens of the /chat "
                            "template head (x%d replicas)", shared, n_replicas,
                        )
                services.append(PagedGenerationService(
                    paged,
                    max_queue=serve.admission_max_queue or None,
                    default_deadline_s=(
                        serve.default_deadline_ms / 1e3
                        if serve.default_deadline_ms > 0 else None
                    ),
                    retry_budget=serve.crash_retry_budget,
                    replica_id=i,
                    tick_stall_budget_s=serve.tick_stall_budget_s,
                    warmup_budget_s=serve.warmup_budget_s,
                ))
            return ReplicaSet(
                services,
                tenant_weights=serve.parsed_tenant_weights(),
                tenant_default_weight=serve.tenant_default_weight,
                tenant_refill_tokens_per_s=serve.tenant_refill_tokens_per_s,
                tenant_burst_tokens=serve.tenant_burst_tokens,
                tenant_headroom=(serve.tenant_headroom
                                 if serve.tenant_headroom >= 0 else None),
                batch_shed_fraction=serve.batch_shed_fraction,
                affinity_stickiness=serve.affinity_stickiness,
                route_prefix_tokens=serve.route_prefix_tokens,
                # replica failure domains: breaker + supervised in-place
                # rebuild + cross-replica failover (REPLICA_* env knobs)
                supervise=serve.replica_supervise,
                probe_interval_s=serve.replica_probe_interval_s,
                breaker_window_s=serve.replica_breaker_window_s,
                breaker_error_rate=serve.replica_breaker_error_rate,
                breaker_min_samples=serve.replica_breaker_min_samples,
                breaker_tick_failures=serve.replica_breaker_tick_failures,
                quarantine_backoff_s=serve.replica_quarantine_backoff_s,
                rebuild_budget=serve.replica_rebuild_budget,
                rebuild_drain_s=serve.replica_rebuild_drain_s,
                failover_budget=serve.replica_failover_budget,
                # resume-by-replay for delivered-token streams
                # (STREAM_RESUME_BUDGET; -1 follows the failover budget)
                stream_resume_budget=(
                    serve.stream_resume_budget
                    if serve.stream_resume_budget >= 0 else None
                ),
                rebuild_workers=serve.replica_rebuild_workers,
            )

        return self._get("generation_service", build)

    @property
    def generator(self):
        def build():
            from sentio_tpu.ops.generator import create_generator

            return create_generator(
                settings=self.settings,
                engine=self.engine,
                service=self.generation_service,
                speculative=self.speculative,
            )

        return self._get("generator", build)

    @property
    def verifier(self):
        def build():
            if not self.settings.generator.use_verifier:
                return None
            from sentio_tpu.ops.verifier import AnswerVerifier

            return AnswerVerifier(generator=self.generator, config=self.settings.generator)

        return self._get("verifier", build)

    @property
    def graph(self):
        def build():
            from sentio_tpu.graph.factory import GraphConfig, build_basic_graph

            return build_basic_graph(
                self.retriever,
                self.generator,
                reranker=self.reranker,
                verifier=self.verifier,
                config=GraphConfig.from_settings(self.settings),
            )

        return self._get("graph", build)

    @property
    def ingestor(self):
        def build():
            from sentio_tpu.ops.ingest import DocumentIngestor

            return DocumentIngestor(
                embedder=self.embedder,
                dense_index=self.dense_index,
                sparse_index=self.sparse_index,
                settings=self.settings,
            )

        return self._get("ingestor", build)

    @property
    def cache_manager(self):
        def build():
            from sentio_tpu.infra.caching import CacheManager

            return CacheManager(config=self.settings.cache)

        return self._get("cache_manager", build)

    @property
    def auth_manager(self):
        def build():
            if not self.settings.auth.enabled:
                return None
            from sentio_tpu.infra.auth import AuthManager

            return AuthManager(config=self.settings.auth)

        return self._get("auth_manager", build)

    @property
    def rate_limiter(self):
        def build():
            from sentio_tpu.infra.security import IPRateLimiter, RateLimitConfig

            limiter = IPRateLimiter(
                default=RateLimitConfig(per_minute=self.settings.serve.rate_limit_default_per_min)
            )
            limiter.configure("/embed", self.settings.serve.rate_limit_embed_per_min)
            return limiter

        return self._get("rate_limiter", build)

    @property
    def metrics(self):
        def build():
            from sentio_tpu.infra.metrics import get_metrics

            return get_metrics()

        return self._get("metrics", build)

    @property
    def chat_handler(self):
        def build():
            from sentio_tpu.serve.handlers import ChatHandler

            return ChatHandler(container=self)

        return self._get("chat_handler", build)

    @property
    def health_handler(self):
        def build():
            from sentio_tpu.serve.handlers import HealthHandler

            return HealthHandler(container=self)

        return self._get("health_handler", build)

    # ------------------------------------------------------------- lifecycle

    def initialize_all(self) -> None:
        """Eagerly build the whole stack in dependency order: mesh → models
        (weights onto HBM) → indexes → graph → handlers. Idempotent."""
        with self._lock:
            if self._initialized:
                return
            t0 = time.perf_counter()
            order = [
                "mesh", "embedder", "dense_index", "sparse_index", "retriever",
                "reranker", "engine", "generation_service", "generator",
                "verifier", "graph", "ingestor", "cache_manager",
                "auth_manager", "rate_limiter", "metrics", "chat_handler",
                "health_handler",
            ]
            for name in order:
                getattr(self, name)
                logger.debug("container: %s ready", name)
            self._initialized = True
            logger.info("container initialized in %.1fs", time.perf_counter() - t0)

    def cleanup(self) -> None:
        with self._lock:
            # the autoscaler stops FIRST (it must not launch or retire
            # mid-teardown); worker_registry closes AFTER the generation
            # service: the ReplicaSet's close reaps workers whose
            # re-registrations the listener may still be fielding
            for name in ("autoscaler", "generation_service", "embedder",
                         "worker_registry"):
                component = self._cache.get(name)
                if component is not None and hasattr(component, "close"):
                    try:
                        component.close()
                    except Exception:  # noqa: BLE001 — shutdown is best-effort
                        logger.warning("%s close failed", name, exc_info=True)
            self._cache.clear()
            self._initialized = False

    def check_dependency_health(self) -> dict[str, Any]:
        """DI-level health map (reference: dependencies.py:346-379 there)."""
        out: dict[str, Any] = {}
        try:
            out["dense_index"] = {"healthy": True, "size": self.dense_index.size}
        except Exception as exc:  # noqa: BLE001
            out["dense_index"] = {"healthy": False, "error": str(exc)}
        try:
            out["sparse_index"] = {"healthy": True, "size": self.sparse_index.size}
        except Exception as exc:  # noqa: BLE001
            out["sparse_index"] = {"healthy": False, "error": str(exc)}
        try:
            vec = self.embedder.embed("health probe")
            out["embedder"] = {"healthy": len(vec) == self.embedder.dimension}
        except Exception as exc:  # noqa: BLE001
            out["embedder"] = {"healthy": False, "error": str(exc)}
        try:
            engine = self.engine
            out["engine"] = (
                {"healthy": True, **engine.device_stats()} if engine is not None
                else {"healthy": True, "provider": self.settings.generator.provider}
            )
        except Exception as exc:  # noqa: BLE001
            out["engine"] = {"healthy": False, "error": str(exc)}
        try:
            service = self.generation_service
            if service is not None:
                out["generation_service"] = {"healthy": True, **service.stats()}
        except Exception as exc:  # noqa: BLE001
            out["generation_service"] = {"healthy": False, "error": str(exc)}
        return out


_container: Optional[DependencyContainer] = None
_container_lock = threading.Lock()


def get_container() -> DependencyContainer:
    global _container
    with _container_lock:
        if _container is None:
            _container = DependencyContainer()
        return _container


def set_container(container: Optional[DependencyContainer]) -> None:
    global _container
    with _container_lock:
        _container = container
