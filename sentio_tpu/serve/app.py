"""HTTP serving surface on aiohttp.web.

Parity with /root/reference/src/api/app.py:250-665 — endpoints ``/chat``
(+SSE streaming), ``/embed``, ``/clear``, ``/health`` ×4, ``/info``,
``/metrics`` + ``/metrics/performance``; per-IP sliding-window rate limits
(10/min ``/embed``, 100/min default, :81-101 there), security-header
middleware (:272-281), central exception handlers (:284-297), lifespan
startup/shutdown (:206-246) — built on aiohttp instead of FastAPI (the only
async HTTP server in the base image), with the TPU inversion: startup eagerly
initializes mesh + weights + indexes via ``DependencyContainer.initialize_all``
so first-request latency is flat.

A minimal built-in chat page at ``/`` replaces the reference's separate
Streamlit app (src/ui/streamlit_app.py there) without adding a dependency.
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import json
import logging
import os
import threading
import time
from pathlib import Path
from typing import Optional

from aiohttp import web

from sentio_tpu.config import Settings, get_settings
from sentio_tpu.infra.exceptions import ErrorHandler, RateLimitError, SentioError
from sentio_tpu.infra.metrics import get_metrics
from sentio_tpu.infra.security import SECURITY_HEADERS, setup_log_sanitization
from sentio_tpu.serve.dependencies import DependencyContainer, get_container, set_container
from sentio_tpu.serve.schemas import (
    MAX_DEADLINE_MS,
    SchemaError,
    parse_chat_request,
    parse_embed_request,
)

logger = logging.getLogger(__name__)

__all__ = ["create_app", "run_server"]

_UI_PAGE = """<!doctype html><html><head><meta charset="utf-8">
<title>sentio-tpu</title><style>
body{font-family:system-ui,sans-serif;max-width:780px;margin:2rem auto;padding:0 1rem}
#log{border:1px solid #ccc;border-radius:8px;padding:1rem;min-height:200px;white-space:pre-wrap}
textarea{width:100%;box-sizing:border-box}
.src{color:#666;font-size:.85em;margin-left:1em}
#health{float:right;font-size:.9em}
#dot{display:inline-block;width:.7em;height:.7em;border-radius:50%;background:#999}
#upl{color:#666;font-size:.85em}
</style></head><body>
<h2>sentio-tpu <span id="health"><span id="dot"></span> <span id="hstat">checking…</span></span></h2>
<p><input type="file" id="file" accept=".txt,.md,.rst,.json,.csv,.pdf,.docx,.html,.htm" multiple>
<button onclick="upload()">Ingest</button> <span id="upl"></span></p>
<div id="log"></div>
<p><textarea id="q" rows="3" placeholder="Ask a question..."></textarea>
<button onclick="send()">Send</button></p>
<script>
async function send(){
  const q=document.getElementById('q').value.trim(); if(!q)return;
  const log=document.getElementById('log');
  log.textContent+='\\n> '+q+'\\n';
  const r=await fetch('/chat',{method:'POST',headers:{'Content-Type':'application/json'},
    body:JSON.stringify({question:q})});
  const d=await r.json();
  log.textContent+=(d.answer||JSON.stringify(d))+'\\n';
  (d.sources||[]).forEach((s,i)=>{log.textContent+='  ['+(i+1)+'] '+(s.metadata.source||s.id)+'\\n'});
}
// client-side chunking + per-chunk /embed, like the reference UI's upload
function chunks(text,size=1500,overlap=200){
  const out=[]; for(let i=0;i<text.length;i+=size-overlap){out.push(text.slice(i,i+size));
    if(i+size>=text.length)break;} return out;
}
// binary formats go whole-file to /upload (server-side parse via the
// docx/pdf readers); text formats keep the chunked /embed flow
async function uploadBinary(f,st){
  for(let tries=0;tries<20;tries++){
    const fd=new FormData(); fd.append('file',f,f.name);
    const r=await fetch('/upload',{method:'POST',body:fd});
    if(r.status===429){
      const wait=parseInt(r.headers.get('Retry-After')||'6',10);
      st.textContent='rate limited; waiting '+wait+'s…';
      await new Promise(res=>setTimeout(res,wait*1000));
      continue;
    }
    let d=null; try{d=await r.json()}catch(e){}
    if(!d) return 'error: HTTP '+r.status;
    const info=(d.files&&d.files[0])||{};
    return info.error?('error: '+info.error):((info.chunks_embedded||0)+' chunks');
  }
  return 'error: rate limited too long';
}
async function upload(){
  const files=document.getElementById('file').files, st=document.getElementById('upl');
  if(!files.length){st.textContent='pick a file first';return}
  let done=0,total=0;
  for(const f of files){
    if(/\\.(pdf|docx|html|htm)$/i.test(f.name)){
      st.textContent='uploading '+f.name+'…';
      st.textContent=f.name+': '+await uploadBinary(f,st);
      continue;
    }
    const text=await f.text(); const parts=chunks(text); total+=parts.length;
    for(let i=0;i<parts.length;i++){
      // the server rate-limits /embed per IP: back off on 429 and retry
      // the SAME chunk instead of silently dropping the document tail
      for(let tries=0;tries<20;tries++){
        const r=await fetch('/embed',{method:'POST',headers:{'Content-Type':'application/json'},
          body:JSON.stringify({content:parts[i],metadata:{source:f.name,chunk:i}})});
        if(r.ok){done++;break}
        if(r.status===429){
          const wait=parseInt(r.headers.get('Retry-After')||'6',10);
          st.textContent='rate limited; waiting '+wait+'s ('+done+'/'+total+')…';
          await new Promise(res=>setTimeout(res,wait*1000));
          continue;
        }
        break; // non-retryable error: count as failed, move on
      }
      st.textContent='ingesting '+done+'/'+total+' chunks…';
    }
  }
  st.textContent='ingested '+done+'/'+total+' chunks';
}
// health badge, polled like the reference sidebar's backend check
async function health(){
  const dot=document.getElementById('dot'), hs=document.getElementById('hstat');
  try{
    const d=await (await fetch('/health')).json();
    dot.style.background=d.status==='healthy'?'#2a2':'#d92';
    hs.textContent=d.status+' · '+Math.round(d.uptime_s)+'s';
  }catch(e){dot.style.background='#d22';hs.textContent='unreachable'}
}
health(); setInterval(health, 15000);
</script></body></html>"""


def _client_ip(request: web.Request, trust_proxy: bool = False) -> str:
    """Socket peer address; X-Forwarded-For only when explicitly deployed
    behind a trusted proxy — otherwise any client could mint a fresh IP per
    request and walk straight past the per-IP rate limiter."""
    peer = request.transport.get_extra_info("peername") if request.transport else None
    ip = peer[0] if peer else "unknown"
    if trust_proxy:
        forwarded = request.headers.get("X-Forwarded-For", "").split(",")[0].strip()
        if forwarded:
            ip = forwarded
    return ip


async def _json_body(request: web.Request):
    """Malformed JSON is a client error (422 with a field list), not a 500."""
    if not request.can_read_body:
        return {}
    try:
        return await request.json()
    except (json.JSONDecodeError, UnicodeDecodeError) as exc:
        raise SchemaError([{"field": "body", "error": f"invalid JSON: {exc}"}]) from exc


@web.middleware
async def error_middleware(request: web.Request, handler):
    """Central exception → JSON error mapping (reference app.py:284-297)."""
    try:
        return await handler(request)
    except SchemaError as exc:
        return web.json_response({"error": "validation_error", "details": exc.errors}, status=422)
    except SentioError as exc:
        resp = web.json_response(exc.to_dict(), status=exc.status)
        # rate limits AND load sheds (ServiceOverloaded → 429/503) carry a
        # retry hint; one mapping so every shed response tells the caller
        # when coming back is worthwhile
        retry = exc.details.get("retry_after_s")
        if retry:
            resp.headers["Retry-After"] = str(max(int(retry), 1))
        return resp
    except web.HTTPException as exc:
        # an HTTPException IS a response — returning it (rather than
        # re-raising) lets the outer security-header middleware stamp it
        return exc
    except Exception as exc:  # noqa: BLE001
        status, body = ErrorHandler.handle(exc)
        return web.json_response(body, status=status)


@web.middleware
async def security_headers_middleware(request: web.Request, handler):
    response = await handler(request)
    for key, value in SECURITY_HEADERS.items():
        response.headers.setdefault(key, value)
    return response


def _make_observability_middleware(container: DependencyContainer):
    @web.middleware
    async def observability_middleware(request: web.Request, handler):
        """Rate limiting + request metrics (reference app.py:259-281).
        Error responses are synthesized in the OUTER error middleware, so
        metrics are recorded in a finally with the mapped status — error
        rates must be visible in /metrics, not just 2xx traffic."""
        path = request.path
        t0 = time.perf_counter()
        status = 500
        metrics = get_metrics()
        # queue-depth gauge: the k8s HPA scales TPU slices on this signal
        # (deploy/kubernetes/hpa.yaml) — probes/metrics scrapes excluded
        work = not path.startswith(("/health", "/metrics"))
        if work:
            metrics.adjust_inflight(+1)
        try:
            if work and path != "/":
                # uploads are ingest work — they share /embed's tight bucket
                endpoint = "/embed" if path in ("/embed", "/upload") else "*"
                ip = _client_ip(request, trust_proxy=container.settings.serve.trust_proxy_headers)
                container.rate_limiter.check(ip, endpoint)
            # request-level OTel span (infra/tracing.py), joining the graph
            # node spans under one trace. The single `enabled` bool keeps
            # the tracing-off path free of span/context overhead.
            from sentio_tpu.infra.tracing import get_tracing

            tracing = get_tracing()
            if tracing.enabled and work:
                with tracing.span(f"http {request.method} {path}",
                                  path=path, method=request.method):
                    response = await handler(request)
            else:
                response = await handler(request)
            status = response.status
            return response
        except SchemaError:
            status = 422
            raise
        except (RateLimitError, SentioError) as exc:
            status = exc.status
            raise
        except web.HTTPException as exc:
            status = exc.status
            raise
        finally:
            if work:
                metrics.adjust_inflight(-1)
            metrics.record_request(path, status, time.perf_counter() - t0)

    return observability_middleware


def _make_auth_middleware(container: DependencyContainer):
    open_paths = ("/health", "/metrics", "/", "/auth/token")

    @web.middleware
    async def auth_middleware(request: web.Request, handler):
        auth = container.auth_manager
        if auth is None or request.path.startswith(open_paths[:2]) or request.path in open_paths:
            return await handler(request)
        header = request.headers.get("Authorization", "")
        api_key = request.headers.get("X-API-Key", "")
        try:
            if header.startswith("Bearer "):
                request["auth"] = auth.verify_token(header[7:])
            elif api_key:
                request["auth"] = auth.verify_api_key(api_key)
            else:
                raise web.HTTPUnauthorized(
                    text=json.dumps({"error": "missing credentials"}),
                    content_type="application/json",
                )
        except web.HTTPException:
            raise
        except Exception:  # noqa: BLE001 — invalid token/key
            raise web.HTTPUnauthorized(
                text=json.dumps({"error": "invalid credentials"}),
                content_type="application/json",
            )
        return await handler(request)

    return auth_middleware


# ---------------------------------------------------------------- endpoints


async def ui_page(request: web.Request) -> web.Response:
    # the inline chat page needs its own CSP (the global default-src 'none'
    # would block the inline script/style)
    return web.Response(
        text=_UI_PAGE,
        content_type="text/html",
        headers={
            "Content-Security-Policy":
                "default-src 'none'; script-src 'unsafe-inline'; "
                "style-src 'unsafe-inline'; connect-src 'self'"
        },
    )


def _resolve_deadline_ts(request: web.Request, req, serve_cfg) -> Optional[float]:
    """Absolute perf_counter deadline for this request: body ``deadline_ms``
    beats the ``X-Deadline-Ms`` header beats the serve default (0 = none).
    A malformed header is ignored rather than 422'd — proxies inject headers
    the caller never wrote."""
    deadline_ms = req.deadline_ms
    if deadline_ms is None:
        raw = request.headers.get("X-Deadline-Ms", "")
        if raw:
            try:
                value = float(raw)
                if 0 < value <= MAX_DEADLINE_MS:
                    deadline_ms = value
            except ValueError:
                pass
    if deadline_ms is None and serve_cfg.default_deadline_ms > 0:
        deadline_ms = serve_cfg.default_deadline_ms
    if deadline_ms is None:
        return None
    return time.perf_counter() + deadline_ms / 1e3


def _resolve_resumable(request: web.Request, req) -> bool:
    """Per-request stream-resumption opt-out: body ``resumable`` beats the
    ``X-Resumable`` header beats the server default (resume). Only the
    explicit falsy header values opt out — proxies inject headers the
    caller never wrote, so anything unrecognized means default."""
    if req.resumable is not None:
        return bool(req.resumable)
    raw = request.headers.get("X-Resumable", "").strip().lower()
    if raw in ("0", "false", "no", "off"):
        return False
    return True


_TENANT_RE = None


def _request_tenant(request: web.Request) -> tuple[str, str]:
    """(tenant, priority) for this request. The tenant key is the auth
    principal when auth is on (a client cannot spoof another tenant by
    header once authenticated), else a header-safe ``X-Tenant`` value, else
    the shared default tenant. ``X-Priority: batch`` opts into the
    shed-earlier tier; anything else is interactive."""
    global _TENANT_RE
    if _TENANT_RE is None:
        import re

        _TENANT_RE = re.compile(r"[A-Za-z0-9._:-]{1,64}")
    from sentio_tpu.runtime.replica import (
        DEFAULT_TENANT,
        PRIORITY_BATCH,
        PRIORITY_INTERACTIVE,
    )

    auth = request.get("auth")
    if auth and auth.get("sub"):
        tenant = f"user:{auth['sub']}"
    else:
        raw = request.headers.get("X-Tenant", "").strip()
        tenant = raw if raw and _TENANT_RE.fullmatch(raw) else DEFAULT_TENANT
    priority = (
        PRIORITY_BATCH
        if request.headers.get("X-Priority", "").strip().lower() == "batch"
        else PRIORITY_INTERACTIVE
    )
    return tenant, priority


async def chat(request: web.Request) -> web.Response:
    container: DependencyContainer = request.app["container"]
    body = await _json_body(request)
    req = parse_chat_request(body, container.settings.serve)
    deadline_ts = _resolve_deadline_ts(request, req, container.settings.serve)
    tenant, priority = _request_tenant(request)
    if req.stream:
        # shed BEFORE response.prepare commits the 200 status line: an SSE
        # stream can only degrade after that, never 429/503
        service = container.peek("generation_service")
        if service is not None and hasattr(service, "check_admission"):
            try:
                if getattr(service, "supports_tenants", False):
                    # replica tier: WFQ tenant check + the routed replica's
                    # own admission, exactly as the submit will see them
                    service.check_admission(
                        deadline_ts, tenant=tenant, priority=priority,
                        prompt=req.question,
                    )
                else:
                    service.check_admission(deadline_ts)
            except SentioError:
                raise  # typed shed/deadline → 429/503/504 with Retry-After
            except Exception:  # noqa: BLE001 — closed/broken paged path
                # the provider still has its contiguous-engine escape hatch;
                # pre-blocking here would 500 a servable stream
                logger.debug("stream admission pre-check skipped", exc_info=True)
        return await _chat_stream(request, container, req, deadline_ts,
                                  tenant=tenant, priority=priority,
                                  resumable=_resolve_resumable(request, req))
    result = await container.chat_handler.process_chat_request(
        question=req.question,
        top_k=req.top_k,
        temperature=req.temperature,
        mode=req.mode,
        thread_id=req.thread_id,
        deadline_ts=deadline_ts,
        tenant=tenant,
        priority=priority,
    )
    return web.json_response(result)


async def _chat_stream(request: web.Request, container: DependencyContainer, req,
                       deadline_ts: Optional[float] = None,
                       tenant: Optional[str] = None,
                       priority: Optional[str] = None,
                       resumable: bool = True) -> web.StreamResponse:
    """SSE token streaming (reference generator.py:298-333 / openai SSE).
    Retrieval + selection run first (blocking stage on a thread), then the
    generator's token iterator is pumped from a worker thread into the
    response via a queue. The flight-record id travels in ``X-Request-Id``
    (client-pinnable via ``thread_id``) so a streamed request's trace is
    retrievable from /debug/flight afterwards.

    **Session continuity**: a replica dying mid-stream does NOT surface
    here when a fronting ReplicaSet can resume it — the token iterator
    below is the set's ``generate_stream``, whose resume-by-replay splices
    the delivered prefix onto a survivor and keeps yielding post-splice
    pieces, so the SSE wire sees one uninterrupted, gap- and
    duplicate-free stream (the keepalive loop bridges the replay-prefill
    gap). Only an opted-out or budget-exhausted stream still gets the
    typed mid-stream error event (wire format unchanged)."""
    import re
    import uuid

    # the id is reflected into a response header — a client-supplied
    # thread_id only pins it when header-safe (no CR/LF/control/unicode),
    # otherwise the client reads the generated id back from X-Request-Id
    request_id = (
        req.thread_id
        if req.thread_id and re.fullmatch(r"[A-Za-z0-9._:-]{1,128}", req.thread_id)
        else uuid.uuid4().hex[:12]
    )
    response = web.StreamResponse(
        headers={
            "Content-Type": "text/event-stream",
            "Cache-Control": "no-cache",
            "Connection": "keep-alive",
            "X-Request-Id": request_id,
        }
    )
    await response.prepare(request)
    loop = asyncio.get_running_loop()
    queue: asyncio.Queue = asyncio.Queue(maxsize=256)
    stop = threading.Event()

    def put(item) -> bool:
        # blocking put with backpressure AND a disconnect escape hatch: when
        # the consumer stops draining (client gone), `stop` is set and the
        # producer exits instead of blocking a pool thread forever
        while not stop.is_set():
            fut = asyncio.run_coroutine_threadsafe(queue.put(item), loop)
            try:
                fut.result(timeout=0.5)
                return True
            except concurrent.futures.TimeoutError:
                # cancel() False = the put actually completed in the race
                # window — treat as delivered or the token would be enqueued
                # twice on retry
                if not fut.cancel():
                    return True
            except Exception:  # noqa: BLE001 — loop closed / cancelled
                return False
        return False

    def produce() -> None:
        # pipeline + degradation live in the handler, mirroring /chat; the
        # handler yields typed events — ("sources", [...]) before the first
        # token, ("token", str) increments, ("verdict", {...}) after the
        # stream (full graph-stage parity: select + verify ride the stream).
        # With VERIFY_MODE=async|gated the handler yields ("done", "")
        # itself as soon as the answer completes, then a trailing
        # ("verify", {...}) verdict — the internal ("eos", "") sentinel
        # (never written to the wire) marks producer exhaustion either way.
        for kind, payload in container.chat_handler.stream_chat_sync(
            question=req.question,
            top_k=req.top_k,
            temperature=req.temperature,
            mode=req.mode,
            request_id=request_id,
            deadline_ts=deadline_ts,
            tenant=tenant,
            priority=priority,
            resumable=resumable,
        ):
            if not put((kind, payload)):
                return
        put(("eos", ""))

    task = loop.run_in_executor(None, produce)
    # SSE liveness: while the producer is silent (long prefill, a slow —
    # or wedged — decode pump), emit comment keepalives so the client can
    # distinguish "still working" from a dead connection and apply its own
    # timeout policy. Comments are invisible to EventSource consumers.
    keepalive_s = getattr(container.settings.serve, "sse_keepalive_s", 0.0)
    wrote_done = False
    try:
        while True:
            try:
                if keepalive_s and keepalive_s > 0:
                    kind, payload = await asyncio.wait_for(
                        queue.get(), timeout=keepalive_s)
                else:
                    kind, payload = await queue.get()
            except asyncio.TimeoutError:
                await response.write(b": keepalive\n\n")
                continue
            if kind == "done":
                # answer complete; the connection STAYS OPEN when a
                # trailing async-verify verdict is still coming (the
                # keepalive loop above bridges the audit decode)
                await response.write(b"data: [DONE]\n\n")
                wrote_done = True
                continue
            if kind == "eos":
                if not wrote_done:
                    await response.write(b"data: [DONE]\n\n")
                break
            await response.write(f"data: {json.dumps({kind: payload})}\n\n".encode())
    finally:
        stop.set()
        # drain so a producer blocked mid-put resolves, then join it
        while not queue.empty():
            queue.get_nowait()
        await task
    await response.write_eof()
    return response


async def embed(request: web.Request) -> web.Response:
    container: DependencyContainer = request.app["container"]
    body = await _json_body(request)
    req = parse_embed_request(body, container.settings.serve)
    stats = await asyncio.to_thread(container.ingestor.ingest_document, req.content, req.metadata)
    get_metrics().record_embeddings(container.settings.embedder.provider, stats.chunks_embedded)
    return web.json_response({"status": "ok", "stats": stats.to_dict()})


async def upload(request: web.Request) -> web.Response:
    """Multipart binary-document ingest — the browser upload path.

    Closes the reference UI's file flow (streamlit_app.py:27-318 there,
    which ingests PDF/TXT client-side): files post as multipart/form-data,
    each part spools to a temp file so the suffix-dispatched readers in
    ops/ingest.py (docx via stdlib zipfile+XML, gated pdf, text formats)
    parse it, then the server chunks + embeds + indexes. Per-file errors
    are reported per file; one bad document never fails the batch."""
    import tempfile

    from sentio_tpu.ops.ingest import SUPPORTED_SUFFIXES

    container: DependencyContainer = request.app["container"]
    if not (request.content_type or "").startswith("multipart/"):
        raise SchemaError([{"field": "body", "error": "multipart/form-data required"}])
    reader = await request.multipart()
    files: list[dict] = []
    # one cap for the WHOLE request (all parts): aiohttp's client_max_size
    # guards read()/post() but multipart() + read_chunk stream unbounded,
    # and a per-part cap would still let one request carry unlimited parts
    cap = container.settings.serve.max_upload_mb * 1024 * 1024
    total = 0
    while True:
        part = await reader.next()
        if part is None:
            break
        keep = part.filename is not None
        name = os.path.basename(part.filename) if keep else ""
        suffix = Path(name).suffix.lower()
        if keep and suffix not in SUPPORTED_SUFFIXES:
            files.append({"filename": name, "error": f"unsupported type {suffix!r}"})
            keep = False
        # EVERY part's bytes count against the cap, including skipped ones —
        # advancing to the next part drains the current one through the
        # server either way, so uncounted skips would let one request
        # stream unlimited data under an 'unsupported type' label
        chunks: list[bytes] = []
        over = False
        while True:
            chunk = await part.read_chunk(64 * 1024)
            if not chunk:
                break
            total += len(chunk)
            if total > cap:
                over = True
                break
            if keep:
                chunks.append(chunk)
        if not keep and not over:
            continue
        if over:
            # stop reading ENTIRELY (don't stream the remainder to /dev/null)
            # but keep the per-file record of everything already ingested so
            # the client knows what not to re-send
            files.append({
                "filename": name,
                "error": f"upload exceeds {container.settings.serve.max_upload_mb} MB request cap",
            })
            return web.json_response({"status": "error", "files": files}, status=413)
        data = b"".join(chunks)
        with tempfile.TemporaryDirectory(prefix="sentio-upload-") as tmp:
            # keep the original (sanitized) name: source metadata and the
            # suffix dispatch in load_file both come from the path
            path = Path(tmp) / name
            path.write_bytes(data)

            def parse_and_index(ing, p=path, src=name):
                docs = ing.load_file(p)
                for doc in docs:
                    # the browser's filename, not the ephemeral temp path
                    doc.metadata["source"] = src
                return ing.ingest_documents(docs)

            try:
                stats = await asyncio.to_thread(parse_and_index, container.ingestor)
            except Exception as exc:  # noqa: BLE001 — per-file isolation
                files.append({"filename": name, "error": str(exc)})
                continue
        entry = {"filename": name, **stats.to_dict()}
        if stats.errors:
            entry["error"] = "; ".join(str(e) for e in stats.errors[:3])
        files.append(entry)
        get_metrics().record_embeddings(
            container.settings.embedder.provider, stats.chunks_embedded
        )
    if not files:
        raise SchemaError([{"field": "file", "error": "no file parts in form data"}])
    ok = any("error" not in f for f in files)
    return web.json_response({"status": "ok" if ok else "error", "files": files},
                             status=200 if ok else 422)


async def clear(request: web.Request) -> web.Response:
    container: DependencyContainer = request.app["container"]
    n = await asyncio.to_thread(container.ingestor.clear)
    return web.json_response({"status": "ok", "documents_removed": n})


async def health(request: web.Request) -> web.Response:
    report = request.app["container"].health_handler.basic()
    # "degraded" (1 ≤ healthy replicas < N) stays 200: the pod is serving
    # at reduced capacity and the supervisor is rebuilding — a 503 here
    # would make k8s restart a half-alive pod and lose the survivors too
    status = 503 if report["status"] == "unhealthy" else 200
    return web.json_response(report, status=status)


async def health_detailed(request: web.Request) -> web.Response:
    report = await request.app["container"].health_handler.detailed()
    status = 200 if report["status"] == "healthy" else 503
    return web.json_response(report, status=status)


async def health_ready(request: web.Request) -> web.Response:
    report = request.app["container"].health_handler.ready()
    return web.json_response(report, status=200 if report["ready"] else 503)


async def health_live(request: web.Request) -> web.Response:
    return web.json_response(request.app["container"].health_handler.live())


def _speculative_info(container: DependencyContainer) -> dict:
    """Honest operator view of the draft-checkpoint knob: active only when
    some serving path actually speculates; otherwise names the exclusion."""
    gen = container.settings.generator
    out: dict = {"draft_configured": bool(gen.draft_checkpoint_path)}
    if not gen.draft_checkpoint_path or gen.provider != "tpu":
        out["active"] = False
        return out
    reason = ""
    if gen.use_paged_decode:
        if container.mesh is not None:
            reason = "device mesh configured (paged speculation is single-chip)"
        elif gen.prefill_chunk:
            reason = ("PREFILL_CHUNK set (chunked prefill excludes paged "
                      "speculation)")
    else:
        # contiguous path (USE_PAGED_KV=0): the SpeculativeDecoder is built
        # only for a single-chip in-process engine — mirror that gating
        # (serve/dependencies.py speculative property) so /info never
        # reports active=true for a decoder that was never constructed
        if container.mesh is not None:
            reason = ("device mesh configured (contiguous speculation is "
                      "single-chip)")
        elif container.engine is None:
            reason = "no in-process engine (contiguous speculation needs one)"
    out["active"] = not reason
    if reason:
        out["ignored_reason"] = reason
    return out


async def info(request: web.Request) -> web.Response:
    container: DependencyContainer = request.app["container"]
    settings = container.settings
    engine = container.engine
    return web.json_response(
        {
            "service": "sentio-tpu",
            "version": __import__("sentio_tpu").__version__,
            "retrieval": {
                "strategy": settings.retrieval.strategy,
                "fusion": settings.retrieval.fusion_method,
                "top_k": settings.retrieval.top_k,
                "corpus_size": container.dense_index.size,
            },
            "reranker": {"enabled": settings.rerank.enabled, "kind": settings.rerank.kind},
            "generator": {
                "provider": settings.generator.provider,
                "preset": settings.generator.model_preset,
                "verifier": settings.generator.use_verifier,
                # a configured draft accelerates BOTH serving paths now —
                # paged (runtime/paged_spec.py, the default) and contiguous
                # (runtime/speculative.py); the genuine exclusions (chunked
                # prefill, device mesh) are surfaced here for operators
                "speculative": _speculative_info(container),
            },
            "device": engine.device_stats() if engine is not None else None,
        }
    )


def _publish_serving_gauges(container: DependencyContainer):
    """Refresh decode-engine metrics at scrape time (occupancy, queue depth,
    free pages — the numbers an HPA or operator actually tunes against;
    prior rounds collected them in the engine but published them nowhere).
    Returns the stats dict (or None) so callers can embed it without a
    second, skew-prone lookup."""
    service = container.peek("generation_service")
    if service is None:  # never built (non-tpu provider / paged off)
        return None
    try:
        stats = service.stats()
    except Exception:  # noqa: BLE001 — metrics must not break the scrape
        return None
    m = get_metrics()
    for key in (
        "active_slots", "queued", "queued_inbox", "free_pages",
        "avg_active_slots", "max_active_slots",
        "ttft_p50_ms", "ttft_p95_ms", "spec_tokens_per_verify",
        # radix prefix cache: fraction of prompt tokens served read-only
        # from cached KV, and the pages the cache currently holds — the
        # two numbers that say whether prefix caching is paying for itself
        "prefix_hit_token_ratio", "prefix_cache_pages", "prefix_cache_nodes",
        # overload posture: admission bound and whether a drain is underway
        "max_queue", "draining",
        # static KV page-pool footprint (bytes) — halves under KV_QUANT=int8
        "pool_hbm_bytes",
    ):
        if key in stats:
            m.set_serving_stat(key, float(stats[key]))
    for event in ("ticks", "completed", "ttft_count",
                  "prefix_hits", "prefix_misses",
                  "prefix_hit_tokens", "prefix_miss_tokens",
                  # raw counters so Prometheus can compute a WINDOWED
                  # tokens-per-verify (the lifetime-average gauge above
                  # flattens draft-quality regressions on long uptimes)
                  "spec_verifies", "spec_emitted",
                  # overload & crash-containment outcomes (lifetime totals;
                  # sentio_tpu_shed_total{reason} carries the fine labels)
                  "shed", "expired", "cancelled", "requeued",
                  "tick_failures", "pump_leaked",
                  # cross-replica failover retries (ReplicaSet layer)
                  "failovers"):
        if event in stats:
            m.bump_serving_total(event, float(stats[event]))
    # pump duty cycle (infra/phases.py): host/device/idle fractions per
    # replica — host-fraction is THE GIL-pressure signal. A bare service
    # exports its own replica row; a ReplicaSet exports one per member.
    replica_rows = stats.get("replicas") or [stats]
    for row in replica_rows:
        duty = row.get("duty_cycle")
        if duty:
            m.record_duty_cycle(row.get("replica", 0), duty)
    # multi-replica tier: the aggregate keeps every dashboard working; the
    # replica-labeled gauge says WHICH replica is hot (occupancy/queue/pool
    # per replica — the signals that justify or indict the router)
    for replica_stats in stats.get("replicas", ()):  # ReplicaSet only
        replica = replica_stats.get("replica", 0)
        for key in ("active_slots", "queued", "queued_inbox", "free_pages",
                    "prefix_cache_pages", "prefix_hit_token_ratio",
                    "pool_hbm_bytes", "ttft_p50_ms", "completed", "shed"):
            if key in replica_stats:
                m.set_replica_stat(replica, key, float(replica_stats[key]))
    return stats


async def metrics_endpoint(request: web.Request) -> web.Response:
    _publish_serving_gauges(request.app["container"])
    return web.Response(
        body=get_metrics().export_prometheus(),
        content_type="text/plain",
        charset="utf-8",
    )


async def metrics_performance(request: web.Request) -> web.Response:
    from sentio_tpu.infra.monitoring import performance_monitor, resource_monitor

    serving = _publish_serving_gauges(request.app["container"])
    return web.json_response(
        {
            "metrics": get_metrics().export_json(),
            "system": performance_monitor.collect_system(),
            "verdict": resource_monitor.health_verdict(),
            "serving": serving,
        }
    )


def _stitch_flight_record(container: DependencyContainer, request_id: str,
                          record: dict) -> dict:
    """Splice worker-side flight truth into a router flight record.

    Thread-replica modes share one flight recorder, so the router record
    already carries the engine section and tick window — stamp
    ``engine_window: "local"`` and return. In process/socket mode the
    engine lives in worker processes: issue ``fetch_flight`` to every
    worker replica (the owner is whichever holds the record), re-base its
    tick timestamps onto the router's perf_counter timeline via the
    ClockSync shift, and merge the engine section in
    (``engine_window: "stitched"``). Workers that are dead or partitioned
    are reported EXPLICITLY in ``replicas_unavailable`` — a half-answer
    must never be silently indistinguishable from a full one. Runs
    blocking RPCs; call from a worker thread."""
    from sentio_tpu.infra.flight import get_flight_recorder

    service = container.peek("generation_service")
    members = list(getattr(service, "_services", None)
                   or ([service] if service is not None else []))
    fetchable = [svc for svc in members
                 if callable(getattr(svc, "fetch_flight", None))]
    if not fetchable:
        record["engine_window"] = "local"
        return record
    router_origin = get_flight_recorder().origin()
    unavailable: list[dict] = []
    stitched = False
    for svc in fetchable:
        try:
            reply = svc.fetch_flight(request_id=request_id)
        except Exception as exc:  # noqa: BLE001 — typed death, timeout, ...
            unavailable.append({
                "replica": getattr(svc, "replica_id", None),
                "error": type(exc).__name__,
            })
            continue
        wrec = reply.get("record")
        if not wrec:
            continue  # this worker never served the request
        shift, bound = svc.flight_shift_s(router_origin)
        engine = dict(wrec.get("engine") or {})
        if engine.get("t_submit_s") is not None:
            engine["t_submit_s"] = round(
                float(engine["t_submit_s"]) + shift, 6)
        merged_engine = dict(record.get("engine") or {})
        merged_engine.update(engine)
        record["engine"] = merged_engine
        ticks = []
        for tick in wrec.get("ticks") or []:
            shifted = dict(tick)
            if "t_s" in shifted:
                shifted["t_s"] = round(float(shifted["t_s"]) + shift, 6)
            ticks.append(shifted)
        if ticks:
            record["ticks"] = ticks
        if wrec.get("ticks_truncated"):
            record["ticks_truncated"] = True
        record["engine_window"] = "stitched"
        record["engine_replica"] = reply.get("replica")
        record["engine_epoch"] = reply.get("epoch")
        if bound is not None:
            record["clock_uncertainty_s"] = round(bound, 6)
        stitched = True
        break
    if not stitched:
        # process/socket mode but no worker produced the record: the
        # router-only view is all there is — say so, loudly
        record["engine_window"] = "remote"
    if unavailable:
        record["replicas_unavailable"] = unavailable
    return record


async def debug_flight(request: web.Request) -> web.Response:
    """One completed (or in-flight) request's flight record: graph node
    timings joined with the engine-tick window its decode rode (occupancy,
    queue depth, prefill/decode splits, page-pool levels) plus TTFT/TPOT.
    In process/socket replica mode the engine tick window lives in the
    worker process — it is fetched on demand and clock-rebased into the
    router record (``engine_window`` says which view you got: ``local`` /
    ``stitched`` / ``remote``, with unreachable workers listed in
    ``replicas_unavailable``). ``?format=chrome`` returns the (stitched)
    record's window as a Chrome/Perfetto trace instead (open the JSON in
    ui.perfetto.dev): the tick slices with their phase decomposition, the
    request span, and the verify verdict on one timeline. Auth-gated when
    auth is enabled — /debug is NOT in the open-paths list, unlike
    /metrics — because records quote request shape and timing."""
    from sentio_tpu.infra.flight import get_flight_recorder

    container: DependencyContainer = request.app["container"]
    request_id = request.match_info["request_id"]
    record = get_flight_recorder().get(request_id)
    if record is None:
        raise web.HTTPNotFound(
            text=json.dumps({"error": f"no flight record for {request_id!r}"}),
            content_type="application/json",
        )
    record = await asyncio.to_thread(
        _stitch_flight_record, container, request_id, record)
    if request.query.get("format") == "chrome":
        from sentio_tpu.infra.chrome_trace import build_chrome_trace

        return web.json_response(build_chrome_trace(
            record.pop("ticks", []), [record]))
    return web.json_response(record)


async def debug_profile(request: web.Request) -> web.Response:
    """On-demand windowed XLA profiling: arm ``jax.profiler`` for
    ``?seconds=N`` (0.1–60, default 3) and stop it, writing the device
    trace under ``?dir=`` / ``JAX_PROFILER_DIR`` / a tmp directory. The
    decode pump wraps every tick in a ``StepTraceAnnotation`` when tracing
    is enabled, so the XLA timeline lines up with flight ticks by step
    number. Single-flight (the profiler is process-global); auth-gated
    like every /debug route. Blocking work runs on a worker thread — the
    event loop keeps serving while the window is open."""
    import tempfile

    from sentio_tpu.infra.tracing import profile_window

    try:
        seconds = float(request.query.get("seconds", "3"))
    except ValueError:
        raise SchemaError([{"field": "seconds",
                            "error": "must be a number"}]) from None
    if not 0.1 <= seconds <= 60.0:
        raise SchemaError([{"field": "seconds",
                            "error": "must be within [0.1, 60]"}])
    container: DependencyContainer = request.app["container"]
    log_dir = (
        request.query.get("dir")
        or container.settings.observability.profiler_dir
        or tempfile.mkdtemp(prefix="sentio-xla-profile-")
    )
    outcome = await asyncio.to_thread(profile_window, seconds, log_dir)
    status = 200 if outcome.get("started") else 409
    return web.json_response(outcome, status=status)


async def auth_token(request: web.Request) -> web.Response:
    """Password → JWT pair (reference auth flow, utils/auth.py there)."""
    container: DependencyContainer = request.app["container"]
    auth = container.auth_manager
    if auth is None:
        raise web.HTTPNotFound(
            text=json.dumps({"error": "auth disabled"}), content_type="application/json"
        )
    body = await _json_body(request)
    username = body.get("username", "")
    password = body.get("password", "")
    tokens = auth.authenticate(username, password)
    return web.json_response(tokens)


# ------------------------------------------------------------------ assembly


def create_app(
    container: Optional[DependencyContainer] = None,
    settings: Optional[Settings] = None,
    initialize: bool = True,
) -> web.Application:
    setup_log_sanitization()
    container = container or DependencyContainer(settings=settings or get_settings())
    set_container(container)

    # security headers outermost so even synthesized error responses carry
    # them; error handling next so every inner exception becomes JSON
    app = web.Application(
        middlewares=[
            security_headers_middleware,
            error_middleware,
            _make_observability_middleware(container),
            _make_auth_middleware(container),
        ],
        # the 1 MiB default stays: /chat + /embed bodies are JSON and should
        # never approach it, and /upload streams multipart with its OWN
        # max_upload_mb cap (multipart() bypasses client_max_size anyway)
    )
    app["container"] = container

    app.router.add_get("/", ui_page)
    app.router.add_post("/chat", chat)
    app.router.add_post("/embed", embed)
    app.router.add_post("/upload", upload)
    app.router.add_post("/clear", clear)
    app.router.add_get("/health", health)
    app.router.add_get("/health/detailed", health_detailed)
    app.router.add_get("/health/ready", health_ready)
    app.router.add_get("/health/live", health_live)
    app.router.add_get("/info", info)
    app.router.add_get("/metrics", metrics_endpoint)
    app.router.add_get("/metrics/performance", metrics_performance)
    app.router.add_get("/debug/flight/{request_id}", debug_flight)
    app.router.add_get("/debug/profile", debug_profile)
    app.router.add_post("/auth/token", auth_token)

    async def on_startup(app: web.Application) -> None:
        if initialize:
            await asyncio.to_thread(container.initialize_all)
        from sentio_tpu.analysis.audit import fence

        if fence.enabled():
            # SENTIO_COMPILE_FENCE=1 (canary/CI pods): warm the paged
            # engine's single-request compile variants, then arm — any
            # LATER XLA compile at a registered jit family hard-fails the
            # tick with the offending family + abstract signature
            def _warm_and_arm() -> None:
                service = container.peek("generation_service")
                if service is None:
                    # nothing to warm (paged path off / lazy init): arming
                    # anyway would fail the FIRST request's cold compile
                    logger.warning(
                        "compile fence: no paged generation service; "
                        "fence NOT armed"
                    )
                    return
                stats = service.warmup()
                logger.info(
                    "compile fence: warmup compiled %d variants over "
                    "%d prompts; arming",
                    stats["xla_compiles"], stats["prompts"],
                )
                fence.arm()

            await asyncio.to_thread(_warm_and_arm)

    async def on_cleanup(app: web.Application) -> None:
        # graceful drain BEFORE teardown: stop admitting (new submits shed
        # 503), give in-flight decodes the configured window to finish, then
        # close — callers mid-generation get answers, not connection resets
        service = container.peek("generation_service")
        if service is not None and hasattr(service, "drain"):
            try:
                outcome = await asyncio.to_thread(
                    service.drain, container.settings.serve.drain_deadline_s
                )
                if not outcome.get("drained", True):
                    logger.warning(
                        "shutdown drain abandoned %d in-flight request(s) "
                        "after %.1fs", outcome.get("abandoned", 0),
                        container.settings.serve.drain_deadline_s,
                    )
            except Exception:  # noqa: BLE001 — shutdown is best-effort
                logger.warning("shutdown drain failed", exc_info=True)
        container.cleanup()
        set_container(None)

    app.on_startup.append(on_startup)
    app.on_cleanup.append(on_cleanup)
    return app


def run_server(settings: Optional[Settings] = None) -> None:
    settings = settings or get_settings()
    app = create_app(settings=settings)
    logger.info("serving on %s:%d", settings.serve.host, settings.serve.port)
    web.run_app(app, host=settings.serve.host, port=settings.serve.port, print=None)
