"""Hygiene rules: monotonic-clock discipline + exception-swallow discipline.

``wall-clock-duration``
    ``time.time()`` is the wrong clock for durations — NTP steps the epoch
    clock backwards/forwards under a live server, which turns TTFT/TPOT
    samples, TTLs, and rate-limit windows into garbage exactly when the
    fleet is being re-synced. Every duration/TTL path must use
    ``time.perf_counter()``. A ``time.time()`` call is a finding unless the
    line (or the line above) carries ``# wall-clock: <reason>`` declaring a
    genuine epoch need (persisted timestamps, tokens crossing processes,
    comparisons against external timestamps).

``baseexception-swallow``
    An ``except BaseException:`` / bare ``except:`` handler whose body never
    ``raise``\\ s swallows ``KeyboardInterrupt`` and ``SystemExit`` — Ctrl-C
    dies silently inside the handler. Cleanup-and-reraise handlers (body
    contains any ``raise``) pass; swallowing handlers must narrow to
    ``except Exception`` or re-raise the exiting exceptions.
"""

from __future__ import annotations

import ast

from sentio_tpu.analysis.findings import Finding, SourceFile

__all__ = ["check_hygiene"]

RULE_CLOCK = "wall-clock-duration"
RULE_SWALLOW = "baseexception-swallow"


def _is_time_time(node: ast.Call) -> bool:
    fn = node.func
    return (
        isinstance(fn, ast.Attribute)
        and fn.attr == "time"
        and isinstance(fn.value, ast.Name)
        and fn.value.id == "time"
    )


def _catches_baseexception(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True  # bare except:
    if isinstance(t, ast.Name) and t.id == "BaseException":
        return True
    if isinstance(t, ast.Tuple):
        return any(
            isinstance(e, ast.Name) and e.id == "BaseException" for e in t.elts
        )
    return False


def _body_reraises(handler: ast.ExceptHandler) -> bool:
    return any(isinstance(n, ast.Raise) for n in ast.walk(handler))


def check_hygiene(tree: ast.Module, src: SourceFile) -> list[Finding]:
    findings: list[Finding] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _is_time_time(node):
            if src.wall_clock_ok(node.lineno):
                continue
            f = src.finding(
                RULE_CLOCK, node.lineno,
                "time.time() in a duration path — NTP steps corrupt the "
                "measurement; use time.perf_counter(), or annotate "
                "`# wall-clock: <reason>` if the epoch is genuinely needed",
            )
            if f is not None:
                findings.append(f)
        elif isinstance(node, ast.ExceptHandler):
            if _catches_baseexception(node) and not _body_reraises(node):
                f = src.finding(
                    RULE_SWALLOW, node.lineno,
                    "except BaseException without re-raise swallows "
                    "KeyboardInterrupt/SystemExit — narrow to Exception or "
                    "re-raise the exiting exceptions",
                )
                if f is not None:
                    findings.append(f)
    return findings
