"""Static lock-order graph + deadlock gate (whole-program).

Builds a digraph over lock identities — ``ClassName.attr`` for
``with self.<attr>:`` acquisitions, ``module.name`` for module-level
locks — with an edge A→B wherever B is acquired while A is held:

* **lexical nesting** — ``with self._a: ... with self._b:`` (and the
  in-order items of ``with self._a, self._b:``);
* **lock-held contracts** — a method whose ``def`` carries
  ``# lock-held: <lock>`` treats that lock as held for its whole body;
* **one level of call propagation** — inside ``with self._a:``, a call
  that resolves through the :mod:`.threads` call graph to a function
  that itself acquires ``_b`` contributes A→B. One level only: deeper
  chains are covered transitively by each callee's own edges, because
  every function's acquisitions are analyzed in its own right.

Any cycle in the digraph is a ``lock-order-inversion`` finding — two
threads walking the cycle from different entry edges can deadlock. Each
edge that closes a cycle is reported at its acquisition site with the
return path spelled out. A lexical self-edge (re-acquiring the lock you
lexically hold) is reported too: on a plain ``threading.Lock`` that is
not an ordering hazard but an immediate single-thread deadlock.

Lock identity is by *name*, not object: every instance of a class shares
one node per lock attribute. That is exactly the granularity the
ordering discipline needs (order between two instances' ``._mutex`` is
as undefined as between two different locks) at the cost of not
distinguishing deliberate instance hierarchies — none exist in this
tree, and one would deserve a rename anyway.

``sentio lint --lock-graph`` dumps the graph (nodes, edges with sites,
cycles) as JSON for offline inspection.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Optional

from sentio_tpu.analysis.findings import Finding
from sentio_tpu.analysis.threads import FuncInfo, Program

__all__ = ["check_lock_order", "build_lock_graph", "LockGraph"]

RULE = "lock-order-inversion"


@dataclass
class LockEdge:
    src_lock: str
    dst_lock: str
    path: str
    line: int
    via: str               # "nested" | "call"
    same_instance: bool    # both locks on the same object (self/self)
    func: str


@dataclass
class LockGraph:
    locks: set[str] = field(default_factory=set)
    edges: list[LockEdge] = field(default_factory=list)
    adj: dict[str, set[str]] = field(default_factory=dict)

    def add(self, edge: LockEdge) -> None:
        self.locks.add(edge.src_lock)
        self.locks.add(edge.dst_lock)
        self.edges.append(edge)
        self.adj.setdefault(edge.src_lock, set()).add(edge.dst_lock)

    def reaches(self, start: str, goal: str) -> Optional[list[str]]:
        """Shortest lock path start→…→goal, or None."""
        if start == goal:
            return [start]
        parent: dict[str, str] = {}
        queue = [start]
        seen = {start}
        while queue:
            cur = queue.pop(0)
            for nxt in sorted(self.adj.get(cur, ())):
                if nxt in seen:
                    continue
                parent[nxt] = cur
                if nxt == goal:
                    path = [goal]
                    while path[-1] != start:
                        path.append(parent[path[-1]])
                    return list(reversed(path))
                seen.add(nxt)
                queue.append(nxt)
        return None

    def cycles(self) -> list[list[str]]:
        """One representative cycle per inversion edge (deduped)."""
        out: list[list[str]] = []
        seen: set[tuple[str, ...]] = set()
        for edge in self.edges:
            back = self.reaches(edge.dst_lock, edge.src_lock)
            if back is None:
                continue
            cycle = back  # dst ... src; closing edge src->dst implied
            canon = tuple(sorted(cycle))
            if canon not in seen:
                seen.add(canon)
                out.append(cycle)
        return out

    def to_json(self) -> dict:
        return {
            "locks": sorted(self.locks),
            "edges": [
                {
                    "from": e.src_lock, "to": e.dst_lock, "path": e.path,
                    "line": e.line, "via": e.via, "func": e.func,
                    "same_instance": e.same_instance,
                }
                for e in sorted(self.edges, key=lambda e: (
                    e.src_lock, e.dst_lock, e.path, e.line))
            ],
            "cycles": self.cycles(),
        }


# ---------------------------------------------------------------- building


def _item_locks(node: ast.With, info: FuncInfo,
                prog: Program) -> list[tuple[str, bool, int]]:
    """(lock id, same_instance, line) for each bare lock item, in
    acquisition order."""
    out = []
    for item in node.items:
        expr = item.context_expr
        if (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self" and info.class_name):
            out.append((f"{info.class_name}.{expr.attr}", True, expr.lineno))
        elif isinstance(expr, ast.Name):
            locks = prog.module_locks.get(info.module, {})
            if expr.id in locks:
                out.append((locks[expr.id], True, expr.lineno))
    return out


def _held_at_entry(info: FuncInfo) -> list[str]:
    """Locks the whole body may assume held, from # lock-held: markers
    (qualified by the enclosing class; the `_locked` suffix convention
    names no specific lock so it cannot seed an ordering edge)."""
    fn = info.node
    held = []
    first_body_line = fn.body[0].lineno if getattr(fn, "body", None) else fn.lineno
    for line in range(fn.lineno, first_body_line + 1):
        marker = info.src.lock_held_marker(line)
        if marker:
            held.append(f"{info.class_name}.{marker}"
                        if info.class_name else marker)
    return held


def _acquired_locks(info: FuncInfo, prog: Program) -> list[tuple[str, int]]:
    """Every lock this function acquires anywhere in its immediate body."""
    out = []
    for w in info.withs:
        for lock, _same, line in _item_locks(w, info, prog):
            out.append((lock, line))
    return out


def build_lock_graph(prog: Program) -> LockGraph:
    graph = LockGraph()
    for info in prog.functions.values():
        _function_edges(prog, info, graph)
    return graph


def _function_edges(prog: Program, info: FuncInfo, graph: LockGraph) -> None:
    base_held = _held_at_entry(info)

    def note(held: list[str], lock: str, line: int, via: str,
             same_instance: bool) -> None:
        for h in held:
            if h == lock and via == "call":
                # a call-propagated same-name edge usually crosses
                # instances (rs helper taking another replica's lock of
                # the same class) — object identity is not static, so
                # only the lexical re-acquisition is reported as a
                # self-deadlock
                continue
            graph.add(LockEdge(
                src_lock=h, dst_lock=lock, path=info.src.rel, line=line,
                via=via, same_instance=same_instance, func=info.key[1],
            ))

    def visit(node: ast.AST, held: list[str]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return  # separate function: runs on its own thread/time
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                visit(item, held)
            inner = list(held)
            for lock, same, line in _item_locks(node, info, prog):
                note(inner, lock, line, "nested", same)
                inner = inner + [lock]
            for stmt in node.body:
                visit(stmt, inner)
            return
        if isinstance(node, ast.Call) and held:
            callee = _resolve(prog, info, node.func)
            if callee is not None:
                ci = prog.functions[callee]
                for lock, line in _acquired_locks(ci, prog):
                    same = (
                        isinstance(node.func, ast.Attribute)
                        and isinstance(node.func.value, ast.Name)
                        and node.func.value.id == "self"
                        and ci.class_name == info.class_name
                    )
                    note(held, lock, node.lineno, "call", same)
        for child in ast.iter_child_nodes(node):
            visit(child, held)

    for child in ast.iter_child_nodes(info.node):
        visit(child, base_held)


def _resolve(prog: Program, info: FuncInfo, fn: ast.expr):
    # lightweight per-call-site resolution: self/cls methods, lexical
    # names, and the unique-name method index. Import-table edges matter
    # little for lock ordering (locks live on classes) and the full
    # resolver needs the build-time tables the Program no longer holds.
    from sentio_tpu.analysis import threads as _t

    if isinstance(fn, ast.Name):
        return info.visible.get(fn.id)
    if isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name):
        if fn.value.id in ("self", "cls") and info.class_name:
            return _t._method_on_class(prog, info.module, info.class_name,
                                       fn.attr)
        if fn.value.id in prog.classes:
            return _t._method_on_class(prog, info.module, fn.value.id,
                                       fn.attr)
    if isinstance(fn, ast.Attribute) and not fn.attr.startswith("__") \
            and fn.attr not in _t._GENERIC_METHODS:
        owners = prog.method_index.get(fn.attr, [])
        if len(owners) == 1:
            return owners[0]
    return None


# ----------------------------------------------------------------- the rule


def check_lock_order(prog: Program) -> list[Finding]:
    graph = build_lock_graph(prog)
    findings: list[Finding] = []
    reported: set[tuple[str, str]] = set()
    src_by_rel = {s.rel: s for _t, s in prog.files}

    for edge in sorted(graph.edges,
                       key=lambda e: (e.path, e.line, e.src_lock, e.dst_lock)):
        if (edge.src_lock, edge.dst_lock) in reported:
            continue
        if edge.src_lock == edge.dst_lock:
            if edge.same_instance and edge.via == "nested":
                reported.add((edge.src_lock, edge.dst_lock))
                src = src_by_rel.get(edge.path)
                f = src and src.finding(
                    RULE, edge.line,
                    f"{edge.func} re-acquires {edge.src_lock} while "
                    f"lexically holding it — immediate deadlock on a "
                    f"non-reentrant lock",
                )
                if f:
                    findings.append(f)
            continue
        back = graph.reaches(edge.dst_lock, edge.src_lock)
        if back is None:
            continue
        reported.add((edge.src_lock, edge.dst_lock))
        src = src_by_rel.get(edge.path)
        if src is None:
            continue
        f = src.finding(
            RULE, edge.line,
            f"{edge.func} acquires {edge.dst_lock} while holding "
            f"{edge.src_lock}, but the reverse order "
            f"{' -> '.join(back)} also exists — two threads entering "
            f"from opposite edges deadlock; pick one global order",
        )
        if f is not None:
            findings.append(f)
    return findings
