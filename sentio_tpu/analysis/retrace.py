"""Retrace lint: AST rules against silent XLA recompile blowups.

Every ``jax.jit`` site promises a bounded set of compile variants, and the
two ways that promise silently breaks are (a) a ``static_argnames`` value
fed from an unbounded host quantity — each distinct value is a fresh XLA
program — and (b) host Python control flow / casts on traced values, which
either fail at trace time or (worse, with weak-type promotion) bake a
constant and recompile per call. A third hazard is a jitted closure reading
mutable host state (``self.<attr>``): the trace bakes the value at first
call and goes stale silently. This module finds all three statically.

Rules
-----

``retrace-unbounded-static``
    A call to a jitted function passes a static argument derived from an
    unbounded host quantity (``len(...)``, raw caller parameters, or
    arithmetic over them) without routing through a bounding helper
    (``bucket_size`` / ``floor_bucket`` / ``_prefill_width`` /
    ``_prior_bucket`` / pow2-``bit_length`` / ``min(x, const)``).

``retrace-traced-branch``
    ``if`` / ``while`` / ternary / ``assert`` on a traced value inside a
    jitted function body — concretization at trace time.

``retrace-traced-cast``
    ``int()`` / ``float()`` / ``bool()`` / ``.item()`` / ``np.asarray()``
    on a traced value inside a jitted function body.

``retrace-host-state``
    A jitted function body references ``self.<attr>`` — mutable host state
    captured by the trace (hoist it to a local before the ``def``, the
    idiom ``_build_fns`` uses everywhere).

Heuristics are deliberately conservative-quiet: unresolvable names count as
bounded, ``.shape`` / ``.ndim`` / ``.dtype`` products of traced arrays count
as static. Residual intentional findings live in the committed baseline.
"""

from __future__ import annotations

import ast
from typing import Optional

from sentio_tpu.analysis.findings import Finding, SourceFile

__all__ = ["check_retrace"]

RULE_STATIC = "retrace-unbounded-static"
RULE_BRANCH = "retrace-traced-branch"
RULE_CAST = "retrace-traced-cast"
RULE_HOST = "retrace-host-state"

# helpers that launder an unbounded quantity into a bounded set of values
BOUNDING_CALLS = {
    "bucket_size",
    "floor_bucket",
    "bit_length",
    "_prefill_width",
    "_prior_bucket",
}
# attributes of traced arrays that are static under trace
STATIC_ATTRS = {"shape", "ndim", "dtype", "size"}
# parameter annotations that mark a hashable-config static (not a count)
CONFIG_ANNOTATIONS = ("Config", "bool", "str", "Mesh", "Callable")


def _is_jax_jit(node: ast.AST) -> bool:
    """``jax.jit`` / ``jit`` / ``pjit.pjit`` reference."""
    if isinstance(node, ast.Attribute) and node.attr in ("jit", "pjit"):
        return True
    return isinstance(node, ast.Name) and node.id in ("jit", "pjit")


def _is_jit_family(node: ast.AST) -> bool:
    """``jit_family(...)`` — the audit registry decorator (analysis/audit/
    registry.py) applies ``jax.jit`` itself, so its sites carry the same
    retrace hazards as bare jit sites and must keep the same coverage."""
    if isinstance(node, ast.Attribute) and node.attr == "jit_family":
        return True
    return isinstance(node, ast.Name) and node.id == "jit_family"


def _jit_decorator_statics(dec: ast.AST) -> Optional[tuple[list[str], list[int]]]:
    """If ``dec`` is a jit decorator → (static_argnames, static_argnums);
    None otherwise."""
    if _is_jax_jit(dec):
        return [], []
    if isinstance(dec, ast.Call):
        fn = dec.func
        is_partial = (
            isinstance(fn, ast.Name) and fn.id == "partial"
        ) or (isinstance(fn, ast.Attribute) and fn.attr == "partial")
        if is_partial and dec.args and _is_jax_jit(dec.args[0]):
            return _extract_statics(dec.keywords)
        if _is_jax_jit(fn) or _is_jit_family(fn):
            # @jax.jit(static_argnames=...) / @jit_family("name", ...) forms
            return _extract_statics(dec.keywords)
    return None


def _extract_statics(keywords: list[ast.keyword]) -> tuple[list[str], list[int]]:
    names: list[str] = []
    nums: list[int] = []
    for kw in keywords:
        if kw.arg == "static_argnames":
            for c in ast.walk(kw.value):
                if isinstance(c, ast.Constant) and isinstance(c.value, str):
                    names.append(c.value)
        elif kw.arg in ("static_argnums", "donate_argnums"):
            if kw.arg == "static_argnums":
                for c in ast.walk(kw.value):
                    if isinstance(c, ast.Constant) and isinstance(c.value, int):
                        nums.append(c.value)
    return names, nums


def _param_names(fn: ast.FunctionDef) -> list[str]:
    args = fn.args
    return [a.arg for a in args.posonlyargs + args.args + args.kwonlyargs]


def _annotation_is_config(fn: ast.FunctionDef, name: str) -> bool:
    for a in fn.args.posonlyargs + fn.args.args + fn.args.kwonlyargs:
        if a.arg == name and a.annotation is not None:
            try:
                text = ast.unparse(a.annotation)
            except Exception:  # noqa: BLE001 — unparse failure degrades to not-a-config-arg  # pragma: no cover
                return False
            return any(tok in text for tok in CONFIG_ANNOTATIONS)
    return False


class _JittedDef:
    def __init__(self, fn: ast.FunctionDef, static_names: list[str],
                 static_nums: list[int]) -> None:
        self.fn = fn
        params = _param_names(fn)
        names = set(static_names)
        for i in static_nums:
            if i < len(params):
                names.add(params[i])
        self.static_names = names
        self.params = params


# --------------------------------------------------------- traced-value rules


def _is_traced_expr(node: ast.AST, traced: set[str]) -> bool:
    """Does evaluating ``node`` concretize a traced value? ``.shape`` /
    ``.ndim`` / ``.dtype`` chains and ``len()`` are static under trace."""
    if isinstance(node, ast.Name):
        return node.id in traced
    if isinstance(node, ast.Attribute):
        if node.attr in STATIC_ATTRS:
            return False
        return _is_traced_expr(node.value, traced)
    if isinstance(node, ast.Call):
        if isinstance(node.func, ast.Name) and node.func.id == "len":
            return False
        return any(
            _is_traced_expr(a, traced)
            for a in list(node.args) + [kw.value for kw in node.keywords]
        ) or _is_traced_expr(node.func, traced)
    if isinstance(node, ast.Compare):
        # `x is None` / `x is not None` tests argument STRUCTURE, not the
        # traced value — the canonical optional-argument idiom
        if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
            return False
        return any(
            _is_traced_expr(c, traced)
            for c in [node.left] + list(node.comparators)
        )
    if isinstance(node, ast.Subscript):
        return _is_traced_expr(node.value, traced) or _is_traced_expr(
            node.slice, traced
        )
    return any(
        _is_traced_expr(c, traced) for c in ast.iter_child_nodes(node)
    )


def _check_jitted_body(src: SourceFile, jd: _JittedDef,
                       findings: list[Finding]) -> None:
    traced = {
        p for p in jd.params
        if p not in jd.static_names and p not in ("self", "cls")
    }

    def add(rule: str, node: ast.AST, msg: str) -> None:
        f = src.finding(rule, node.lineno, msg)
        if f is not None:
            findings.append(f)

    def visit(node: ast.AST, traced: set[str], in_nested: bool) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                # nested fns (scan bodies): unknown param tracedness — only
                # the host-state rule keeps applying inside them
                visit(child, set(), True)
                continue
            if isinstance(child, ast.Assign) and not in_nested:
                if _is_traced_expr(child.value, traced):
                    for tgt in child.targets:
                        for n in ast.walk(tgt):
                            if isinstance(n, ast.Name):
                                traced.add(n.id)
            if isinstance(child, (ast.If, ast.While)):
                if _is_traced_expr(child.test, traced):
                    add(RULE_BRANCH, child,
                        f"Python {'if' if isinstance(child, ast.If) else 'while'} "
                        f"on a traced value inside jitted "
                        f"`{jd.fn.name}` — use jnp.where / lax.cond")
            if isinstance(child, ast.IfExp) and _is_traced_expr(child.test, traced):
                add(RULE_BRANCH, child,
                    f"ternary on a traced value inside jitted `{jd.fn.name}`")
            if isinstance(child, ast.Assert) and _is_traced_expr(child.test, traced):
                add(RULE_BRANCH, child,
                    f"assert on a traced value inside jitted `{jd.fn.name}`")
            if isinstance(child, ast.Call):
                fn = child.func
                if (isinstance(fn, ast.Name) and fn.id in ("int", "float", "bool")
                        and child.args
                        and _is_traced_expr(child.args[0], traced)):
                    add(RULE_CAST, child,
                        f"{fn.id}() concretizes a traced value inside jitted "
                        f"`{jd.fn.name}`")
                if (isinstance(fn, ast.Attribute) and fn.attr == "item"
                        and _is_traced_expr(fn.value, traced)):
                    add(RULE_CAST, child,
                        f".item() fetches a traced value inside jitted "
                        f"`{jd.fn.name}`")
                if (isinstance(fn, ast.Attribute)
                        and fn.attr in ("asarray", "array")
                        and isinstance(fn.value, ast.Name)
                        and fn.value.id in ("np", "numpy")
                        and child.args
                        and _is_traced_expr(child.args[0], traced)):
                    add(RULE_CAST, child,
                        f"np.{fn.attr}() forces a traced value to host inside "
                        f"jitted `{jd.fn.name}`")
            if isinstance(child, ast.Attribute):
                if (isinstance(child.value, ast.Name)
                        and child.value.id == "self"):
                    add(RULE_HOST, child,
                        f"jitted `{jd.fn.name}` reads `self.{child.attr}` — "
                        f"the trace bakes mutable host state; hoist to a "
                        f"local before the def")
                    continue  # don't double-report nested attribute chains
            visit(child, traced, in_nested)

    # the fn node is the root: its direct children (the body statements) and
    # everything below get visited uniformly
    visit(jd.fn, traced, False)


# ------------------------------------------------------ unbounded-static rule


class _BoundednessEnv:
    """Name resolution scope: assignments within the enclosing function."""

    def __init__(self, enclosing: Optional[ast.FunctionDef]) -> None:
        self.assignments: dict[str, list[ast.AST]] = {}
        self.params: set[str] = set()
        self.fn = enclosing
        if enclosing is not None:
            self.params = set(_param_names(enclosing)) - {"self", "cls"}
            for node in ast.walk(enclosing):
                if isinstance(node, ast.Assign):
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            self.assignments.setdefault(tgt.id, []).append(
                                node.value
                            )
                elif isinstance(node, ast.AugAssign) and isinstance(
                    node.target, ast.Name
                ):
                    self.assignments.setdefault(node.target.id, []).append(
                        node.value
                    )


def _is_unbounded(node: ast.AST, env: _BoundednessEnv, depth: int = 0,
                  seen: Optional[set[str]] = None) -> bool:
    """True when ``node`` can take unboundedly many distinct values per
    process (each one a fresh compile of the jitted callee)."""
    if depth > 6:
        return False  # resolution too deep: stay quiet
    seen = seen or set()
    if isinstance(node, ast.Constant):
        return False
    if isinstance(node, ast.Call):
        fn = node.func
        name = fn.id if isinstance(fn, ast.Name) else (
            fn.attr if isinstance(fn, ast.Attribute) else ""
        )
        if name in BOUNDING_CALLS:
            return False
        if name == "min":
            # min(x, bound) is bounded as soon as ANY arm is
            return all(
                _is_unbounded(a, env, depth + 1, seen) for a in node.args
            )
        if name == "len":
            return True
        if name in ("int", "max", "abs", "round"):
            return any(
                _is_unbounded(a, env, depth + 1, seen) for a in node.args
            )
        return False  # unknown call: stay quiet
    if isinstance(node, ast.Name):
        if node.id in seen:
            return False
        seen = seen | {node.id}
        exprs = env.assignments.get(node.id)
        if exprs:
            return any(_is_unbounded(e, env, depth + 1, seen) for e in exprs)
        if node.id in env.params:
            # raw caller input reaching a static arg — unless annotated as a
            # hashable config type
            return not (env.fn is not None
                        and _annotation_is_config(env.fn, node.id))
        return False
    if isinstance(node, (ast.BinOp, ast.UnaryOp)):
        return any(
            _is_unbounded(c, env, depth + 1, seen)
            for c in ast.iter_child_nodes(node)
            if not isinstance(c, ast.operator)
        )
    if isinstance(node, ast.IfExp):
        return _is_unbounded(node.body, env, depth + 1, seen) or _is_unbounded(
            node.orelse, env, depth + 1, seen
        )
    return False


def _check_static_callsites(tree: ast.Module, src: SourceFile,
                            registry: dict[str, _JittedDef],
                            findings: list[Finding]) -> None:
    """Every call whose callee name resolves to a jitted def: classify the
    expressions feeding its static args."""

    def enclosing_functions(t: ast.Module):
        stack: list[tuple[ast.AST, Optional[ast.FunctionDef]]] = [(t, None)]
        while stack:
            node, fn = stack.pop()
            for child in ast.iter_child_nodes(node):
                child_fn = fn
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    child_fn = child
                stack.append((child, child_fn))
            if isinstance(node, ast.Call):
                yield node, fn

    for call, fn in enclosing_functions(tree):
        callee = None
        if isinstance(call.func, ast.Name):
            callee = call.func.id
        elif isinstance(call.func, ast.Attribute):
            callee = call.func.attr
        jd = registry.get(callee or "")
        if jd is None or not jd.static_names:
            continue
        if fn is not None and jd.fn is fn:
            continue  # recursive mention, not a callsite
        env = _BoundednessEnv(fn)
        checked: list[tuple[str, ast.AST]] = []
        for kw in call.keywords:
            if kw.arg in jd.static_names:
                checked.append((kw.arg, kw.value))
        for i, arg in enumerate(call.args):
            if i < len(jd.params) and jd.params[i] in jd.static_names:
                checked.append((jd.params[i], arg))
        for name, value in checked:
            if _is_unbounded(value, env):
                f = src.finding(
                    RULE_STATIC, value.lineno,
                    f"static arg `{name}` of jitted `{jd.fn.name}` fed from "
                    f"an unbounded host quantity — every distinct value "
                    f"compiles a fresh XLA program; route through "
                    f"bucket_size/pow2 bucketing",
                )
                if f is not None:
                    findings.append(f)


# ----------------------------------------------------------------- entrypoint


def check_retrace(tree: ast.Module, src: SourceFile) -> list[Finding]:
    findings: list[Finding] = []
    registry: dict[str, _JittedDef] = {}

    # pass 1: jitted defs (any nesting depth) + alias registration
    defs_by_name: dict[str, ast.FunctionDef] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef):
            defs_by_name.setdefault(node.name, node)
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef):
            for dec in node.decorator_list:
                statics = _jit_decorator_statics(dec)
                if statics is not None:
                    jd = _JittedDef(node, *statics)
                    registry[node.name] = jd
                    break
        elif isinstance(node, ast.Assign):
            # self._fwd = jax.jit(fwd) / self._step = step_n alias forms
            value = node.value
            target_names = [
                t.attr for t in node.targets if isinstance(t, ast.Attribute)
            ] + [t.id for t in node.targets if isinstance(t, ast.Name)]
            if isinstance(value, ast.Call) and _is_jax_jit(value.func):
                names, nums = _extract_statics(value.keywords)
                inner = value.args[0] if value.args else None
                if isinstance(inner, ast.Name) and inner.id in defs_by_name:
                    jd = _JittedDef(defs_by_name[inner.id], names, nums)
                    registry.setdefault(inner.id, jd)
                    for tn in target_names:
                        registry.setdefault(tn, jd)
            elif isinstance(value, ast.Name) and value.id in registry:
                for tn in target_names:
                    registry.setdefault(tn, registry[value.id])

    # pass 2: body rules per jitted def (dedupe shared defs)
    seen_defs: set[int] = set()
    for jd in registry.values():
        if id(jd.fn) in seen_defs:
            continue
        seen_defs.add(id(jd.fn))
        _check_jitted_body(src, jd, findings)

    # pass 3: static-arg boundedness at every callsite
    _check_static_callsites(tree, src, registry, findings)
    return findings
