"""Static analysis + runtime sanitizers for the serving runtime's contracts.

The engine's correctness surfaces live in comments: ``runtime/service.py``
declares the engine pump-thread-only, the radix cache depends on refcount
pinning and page-pool conservation, and every ``jax.jit`` site silently
promises bounded compile variants. This package makes those contracts
machine-checked, twice over:

* ``sentio lint`` (:mod:`sentio_tpu.analysis.runner`) — an AST lint over the
  source tree: retrace hazards at jit sites (:mod:`.retrace`), lock
  discipline against ``guarded-by`` annotations (:mod:`.locks`), and
  wall-clock / exception hygiene (:mod:`.hygiene`). Findings gate against a
  committed baseline (``analysis/baseline.json``) so the check starts green
  and only ratchets down.
* ``SENTIO_SANITIZE=1`` (:mod:`sentio_tpu.analysis.sanitizer`) — opt-in
  runtime checks: engine entry points assert the single-driver-thread
  contract, annotated locks record ownership so lock-held helpers can
  assert it, and every engine tick verifies page-pool conservation and
  radix refcount consistency.
* ``sentio audit`` (:mod:`sentio_tpu.analysis.audit`) — the artifact-level
  half the AST cannot see: every ``jit_family`` site is AOT-lowered over
  its declared variant space on a tiny CPU config and gated against the
  committed ``analysis/compile_manifest.json`` (variant count, donation
  aliasing, mesh sharding, static HBM). ``SENTIO_COMPILE_FENCE=1`` arms
  the runtime half: post-warmup recompiles become hard errors.

``sentio check`` runs lint + audit as one gate.

Annotation guide
================

``# guarded-by: <lock>`` — trailing comment on a ``self.<attr> = ...``
assignment (conventionally in ``__init__``). Declares that every later
access of ``self.<attr>`` from methods of that class must sit lexically
inside a ``with self.<lock>:`` block::

    class Service:
        def __init__(self):
            self._mutex = threading.Lock()
            self._inbox = []  # guarded-by: _mutex

Two escape hatches, both of which the checker treats as "the lock is
already held here":

* a method whose name ends in ``_locked`` (e.g. ``_evict_locked``);
* a method whose ``def`` line carries ``# lock-held: <lock>``.

The special lock name ``engine-thread`` marks state owned by a single
driver thread rather than a mutex (the paged engine, the radix cache).
The static checker skips ``with``-block validation for those attributes —
thread identity is not lexical — and the runtime sanitizer enforces the
contract instead: under ``SENTIO_SANITIZE=1`` every mutating engine entry
point asserts it runs on the bound driver thread (the serving pump rebinds
ownership at pump start; see :func:`.sanitizer.bind_engine_owner`).

``# wall-clock: <reason>`` — trailing comment allowing a ``time.time()``
call that genuinely needs the epoch (persisted timestamps, tokens shared
across processes, comparisons against external timestamps). Durations and
TTLs must use ``time.perf_counter()``; an unannotated ``time.time()`` is a
finding.

``# lint: allow(<rule>)`` — trailing comment suppressing one named rule on
that line, for deliberate, commented exceptions (e.g. a GIL-atomic
telemetry read of a guarded field).
"""

from sentio_tpu.analysis.findings import (
    Finding,
    diff_baseline,
    load_baseline,
    save_baseline,
)
from sentio_tpu.analysis.runner import lint_paths, run_gate

__all__ = [
    "Finding",
    "diff_baseline",
    "load_baseline",
    "save_baseline",
    "lint_paths",
    "run_gate",
]
