"""Phase-timer discipline: no phase region entered while holding a lock.

The tick-phase attribution layer (infra/phases.py) decomposes pump wall
time into named phases. A phase-timer region (``PhaseTimer``/``.phase(...)``
context) opened while an annotated lock is held silently folds LOCK-WAIT
and critical-section time into whatever phase happens to be open — the
decomposition then under-reports contention exactly where it matters.
The discipline: start the timer BEFORE acquiring (the lock wait is then
part of the phase being measured, e.g. ``inbox_drain`` covering its mutex
section), never the other way around; if lock-wait itself needs a number,
it gets a dedicated phase, not a side effect.

``phase-timer-under-lock``
    A ``with <timer>.phase(...)`` (or ``with PhaseTimer(...)``) entered
    while a ``with self.<lock>:`` block is lexically open, where
    ``<lock>`` is any lock named by a ``# guarded-by:`` annotation in the
    same module (the same source of truth as the lock-discipline checker,
    analysis/locks.py). Methods whose name ends in ``_locked`` — or that
    carry a ``# lock-held:`` marker — hold their caller's lock by
    contract, so a phase region anywhere in their body fires too.

Suppression: the standard inline ``# lint: allow(<rule>)`` marker.
"""

from __future__ import annotations

import ast

from sentio_tpu.analysis.findings import Finding, SourceFile
from sentio_tpu.analysis.locks import _method_held_locks, collect_guarded

__all__ = ["check_phase_timer"]

RULE_PHASE_LOCK = "phase-timer-under-lock"


def _is_phase_ctx(expr: ast.expr) -> bool:
    """``<anything>.phase(...)`` or ``PhaseTimer(...)`` used as a context
    expression."""
    if not isinstance(expr, ast.Call):
        return False
    func = expr.func
    if isinstance(func, ast.Attribute) and func.attr == "phase":
        return True
    if isinstance(func, ast.Name) and func.id == "PhaseTimer":
        return True
    if isinstance(func, ast.Attribute) and func.attr == "PhaseTimer":
        return True
    return False


def _is_lock_item(expr: ast.expr, lock_names: set[str]) -> bool:
    """``self.<lock>`` for an annotated lock name."""
    return (
        isinstance(expr, ast.Attribute)
        and isinstance(expr.value, ast.Name)
        and expr.value.id == "self"
        and expr.attr in lock_names
    )


def check_phase_timer(tree: ast.Module, src: SourceFile) -> list[Finding]:
    findings: list[Finding] = []
    lock_names: set[str] = set()
    for gc in collect_guarded(tree, src).values():
        lock_names.update(gc.guarded.values())
    if not lock_names:
        # no annotated locks in this module — nothing to hold
        return findings

    def report(node: ast.AST) -> None:
        f = src.finding(
            RULE_PHASE_LOCK, node.lineno,
            "phase-timer region entered while holding an annotated lock — "
            "lock wait/hold time silently folds into the open phase; start "
            "the timer before acquiring (timing lock-wait is a dedicated "
            "phase, not a side effect)",
        )
        if f is not None:
            findings.append(f)

    def visit(node: ast.AST, held: bool) -> None:
        if isinstance(node, (ast.With, ast.AsyncWith)):
            # items evaluate left to right: `with self._mutex, t.phase():`
            # enters the phase region with the lock already held
            inner_held = held
            for item in node.items:
                expr = item.context_expr
                if inner_held and _is_phase_ctx(expr):
                    report(expr)
                if _is_lock_item(expr, lock_names):
                    inner_held = True
            for stmt in node.body:
                visit(stmt, inner_held)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested defs run later on whatever thread calls them; only
            # their own markers (`_locked` suffix, `# lock-held:`) declare
            # a held lock
            nested_held = bool(_method_held_locks(node, src))
            for child in ast.iter_child_nodes(node):
                visit(child, nested_held)
            return
        if isinstance(node, ast.Lambda):
            visit(node.body, False)
            return
        for child in ast.iter_child_nodes(node):
            visit(child, held)

    visit(tree, False)
    return findings
