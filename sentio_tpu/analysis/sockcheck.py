"""Socket deadline discipline: no unbounded network blocking.

The multi-host worker tier (runtime/transport.py) exists because the
network fails in ways a pipe never does — and the nastiest failure is the
one that raises nothing: a half-open link where a blocking ``recv`` (or a
``send`` into a full buffer) simply never returns. The transport module is
the ONE vetted place that machinery lives: every blocking socket op there
carries a deadline (fixed ``settimeout`` poll + explicit frame deadlines),
and everything above it observes liveness through status-frame staleness
and the partition watchdog. This rule keeps anyone from quietly opening a
raw, deadline-free socket elsewhere in the tree — the ``join-no-timeout``
precedent, applied to the network:

``socket-no-timeout``
    Fires on:

    * a ``socket.socket(...)`` (or bare ``socket(...)``) construction in a
      function that never wires a deadline — no ``.settimeout(...)`` with
      a non-``None`` argument and no ``setsockopt`` with
      ``SO_RCVTIMEO``/``SO_SNDTIMEO`` anywhere in the same scope;
    * a ``create_connection(...)`` call with no ``timeout=`` argument in
      an unwired scope (its default is socket-global, i.e. usually
      blocking-forever);
    * a ``.recv(...)`` call on a socket-shaped receiver (a name containing
      ``sock`` or ``conn``) lexically inside a ``while`` loop in an
      unwired scope — the classic zero-timeout read loop that hangs a
      reader thread on a stalled link.

    The vetted transport internals (whose deadlines are enforced by
    explicit ``perf_counter`` bookkeeping the AST cannot see) carry the
    standard inline ``# lint: allow(socket-no-timeout)`` marker.

Suppression: the standard inline ``# lint: allow(socket-no-timeout)``
marker.
"""

from __future__ import annotations

import ast

from sentio_tpu.analysis.findings import Finding, SourceFile

__all__ = ["check_sockets"]

RULE = "socket-no-timeout"

# setsockopt option names that count as deadline wiring
_DEADLINE_OPTS = ("SO_RCVTIMEO", "SO_SNDTIMEO")

# receiver-name fragments that mark a .recv() call as socket-shaped
_SOCKETISH = ("sock", "conn")


def _call_name(node: ast.Call) -> str:
    if isinstance(node.func, ast.Attribute):
        return node.func.attr
    if isinstance(node.func, ast.Name):
        return node.func.id
    return ""


def _receiver_name(node: ast.Call) -> str:
    """Trailing name of the object a method is called on:
    ``self._sock.recv(...)`` → ``_sock``; bare calls → ''."""
    if not isinstance(node.func, ast.Attribute):
        return ""
    obj = node.func.value
    if isinstance(obj, ast.Attribute):
        return obj.attr
    if isinstance(obj, ast.Name):
        return obj.id
    return ""


def _wires_deadline(node: ast.Call) -> bool:
    """True when this call itself establishes a socket deadline."""
    name = _call_name(node)
    if name == "settimeout":
        args = list(node.args) + [kw.value for kw in node.keywords]
        if not args:
            return False
        first = args[0]
        return not (isinstance(first, ast.Constant) and first.value is None)
    if name == "setsockopt":
        for arg in ast.walk(node):
            if isinstance(arg, ast.Attribute) and arg.attr in _DEADLINE_OPTS:
                return True
            if isinstance(arg, ast.Name) and arg.id in _DEADLINE_OPTS:
                return True
    return False


def _has_timeout_kwarg(node: ast.Call) -> bool:
    return any(kw.arg == "timeout" for kw in node.keywords)


def check_sockets(tree: ast.Module, src: SourceFile) -> list[Finding]:
    findings: list[Finding] = []

    def scope_nodes(scope: ast.AST):
        """Direct statements of this scope, not descending into nested
        function scopes (each function wires — or fails to wire — its own
        deadlines)."""
        stack = list(ast.iter_child_nodes(scope))
        while stack:
            node = stack.pop()
            yield node
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                stack.extend(ast.iter_child_nodes(node))

    def check_scope(scope: ast.AST) -> None:
        wired = any(
            isinstance(n, ast.Call) and _wires_deadline(n)
            for n in scope_nodes(scope)
        )
        # second pass: flag creations and recv loops in unwired scopes
        def visit(node: ast.AST, in_while: bool) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                check_scope(node)
                return
            if isinstance(node, ast.While):
                in_while = True
            if isinstance(node, ast.Call) and not wired:
                name = _call_name(node)
                f = None
                if name == "socket":
                    f = src.finding(
                        RULE, node.lineno,
                        "socket.socket() with no settimeout/SO_* deadline "
                        "wiring in scope — a half-open peer hangs every "
                        "blocking op forever; wire a timeout (see "
                        "runtime/transport.py) or annotate the vetted site",
                    )
                elif name == "create_connection" and not _has_timeout_kwarg(
                        node):
                    f = src.finding(
                        RULE, node.lineno,
                        "create_connection() without timeout= in an "
                        "unwired scope — the connect (and every later op) "
                        "can block forever on a partitioned host",
                    )
                elif (name == "recv" and in_while
                      and any(s in _receiver_name(node).lower()
                              for s in _SOCKETISH)):
                    f = src.finding(
                        RULE, node.lineno,
                        "zero-timeout recv loop on a socket — a stalled "
                        "link wedges this thread with no error ever "
                        "raised; poll with a deadline and surface the "
                        "staleness (see SocketTransport._recv_exact)",
                    )
                if f is not None:
                    findings.append(f)
            for child in ast.iter_child_nodes(node):
                visit(child, in_while)

        for stmt in ast.iter_child_nodes(scope):
            visit(stmt, False)

    check_scope(tree)
    return findings
