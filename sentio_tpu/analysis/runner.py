"""Lint orchestration: file walking, rule dispatch, baseline gate, CLI.

``lint_paths`` parses each ``.py`` file once and fans it through every rule
module; ``run_gate`` wraps that in the baseline ratchet (new findings fail,
baselined findings pass, fixed-but-still-baselined entries report as stale
so the baseline only shrinks). ``main`` is the ``sentio lint`` entry point.
"""

from __future__ import annotations

import argparse
import ast
import json
import sys
from dataclasses import dataclass, field
from pathlib import Path
from typing import Optional, Sequence

from sentio_tpu.analysis.findings import (
    Finding,
    SourceFile,
    diff_baseline,
    load_baseline,
    save_baseline,
)
from sentio_tpu.analysis.blocking import check_blocking
from sentio_tpu.analysis.failures import (
    FAILURE_RULE_IDS,
    build_failure_graph,
    check_failures,
)
from sentio_tpu.analysis.forkcheck import check_fork
from sentio_tpu.analysis.hygiene import check_hygiene
from sentio_tpu.analysis.lockorder import build_lock_graph, check_lock_order
from sentio_tpu.analysis.locks import check_locks
from sentio_tpu.analysis.phasing import check_phase_timer
from sentio_tpu.analysis.retrace import check_retrace
from sentio_tpu.analysis.sockcheck import check_sockets
from sentio_tpu.analysis.telemetry import check_telemetry
from sentio_tpu.analysis.threads import build_program, check_thread_model

__all__ = ["lint_paths", "run_gate", "main", "DEFAULT_BASELINE"]

PACKAGE_ROOT = Path(__file__).resolve().parents[1]  # sentio_tpu/
REPO_ROOT = PACKAGE_ROOT.parent
DEFAULT_BASELINE = Path(__file__).resolve().parent / "baseline.json"

RULES = (check_retrace, check_locks, check_hygiene, check_blocking,
         check_phase_timer, check_fork, check_sockets, check_telemetry)

# whole-program rules: run once over every parsed file together, so the
# thread-role call graph, the lock-order digraph, and the exception-flow
# escape analysis see cross-module paths
PROGRAM_RULES = (check_thread_model, check_lock_order, check_failures)

#: every finding id the analyzer can emit (--json reports this so gate
#: consumers know which rules ran; syntax-error is the parse fallback)
RULE_IDS = (
    "retrace-unbounded-static", "retrace-traced-branch",
    "retrace-traced-cast", "retrace-host-state",
    "lock-discipline",
    "wall-clock-duration", "baseexception-swallow",
    "join-no-timeout", "supervisor-blocking-wait",
    "phase-timer-under-lock",
    "no-fork",
    "socket-no-timeout",
    "telemetry-unbounded-labels",
    "thread-role", "cross-thread-race",
    "lock-order-inversion",
) + FAILURE_RULE_IDS + (
    "syntax-error",
)


def _iter_py_files(path: Path):
    if path.is_file():
        if path.suffix == ".py":
            yield path
        return
    for p in sorted(path.rglob("*.py")):
        if "__pycache__" not in p.parts:
            yield p


def _rel(path: Path) -> str:
    try:
        return path.resolve().relative_to(REPO_ROOT).as_posix()
    except ValueError:
        return path.as_posix()


def _parse_file(path: Path) -> tuple[Optional[ast.Module], SourceFile,
                                     list[Finding]]:
    text = path.read_text(encoding="utf-8", errors="replace")
    src = SourceFile(path=path, rel=_rel(path), text=text)
    try:
        return ast.parse(text), src, []
    except SyntaxError as exc:
        return None, src, [Finding(
            rule="syntax-error", path=src.rel,
            line=exc.lineno or 1,
            message=f"file does not parse: {exc.msg}",
            context=src.line_text(exc.lineno or 1).strip(),
        )]


def lint_file(path: Path) -> list[Finding]:
    """Per-file rules only (whole-program rules need ``lint_paths``)."""
    tree, src, findings = _parse_file(path)
    if tree is not None:
        for rule in RULES:
            findings.extend(rule(tree, src))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


def parse_paths(paths: Sequence[str | Path]) -> tuple[
        list[tuple[ast.Module, SourceFile]], list[Finding]]:
    """Parse every .py under ``paths`` once; returns (files, parse errors)."""
    files: list[tuple[ast.Module, SourceFile]] = []
    findings: list[Finding] = []
    for raw in paths:
        for p in _iter_py_files(Path(raw)):
            tree, src, errs = _parse_file(p)
            findings.extend(errs)
            if tree is not None:
                files.append((tree, src))
    return files, findings


def lint_paths(paths: Sequence[str | Path]) -> list[Finding]:
    """All rules: per-file rules on each file, then the whole-program
    rules (thread-role/race, lock order) over every file together."""
    files, findings = parse_paths(paths)
    for tree, src in files:
        for rule in RULES:
            findings.extend(rule(tree, src))
    program = build_program(files)
    for prule in PROGRAM_RULES:
        findings.extend(prule(program))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


@dataclass
class GateResult:
    findings: list[Finding] = field(default_factory=list)
    new: list[Finding] = field(default_factory=list)
    matched: list[Finding] = field(default_factory=list)
    stale: list[dict] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.new

    def summary(self) -> str:
        return (
            f"{len(self.findings)} findings: {len(self.new)} new, "
            f"{len(self.matched)} baselined, {len(self.stale)} stale "
            f"baseline entries"
        )


def run_gate(
    paths: Optional[Sequence[str | Path]] = None,
    baseline_path: Optional[str | Path] = None,
    only_rules: Optional[set] = None,
) -> GateResult:
    """Lint ``paths`` (default: the installed ``sentio_tpu`` package) and
    diff against the committed baseline. ``ok`` iff no NEW findings.
    ``only_rules`` restricts BOTH the reported findings and the baseline
    entries they diff against (``sentio lint --failures``)."""
    paths = list(paths) if paths else [PACKAGE_ROOT]
    baseline = load_baseline(baseline_path or DEFAULT_BASELINE)
    findings = lint_paths(paths)
    if only_rules:
        findings = [f for f in findings if f.rule in only_rules]
        baseline = [e for e in baseline if e.get("rule") in only_rules]
    new, matched, stale = diff_baseline(findings, baseline)
    return GateResult(findings=findings, new=new, matched=matched, stale=stale)


def main(argv: Optional[list[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="sentio lint",
        description="AST lint for retrace / lock-discipline / clock / "
                    "exception hazards, gated on a committed baseline",
    )
    parser.add_argument("paths", nargs="*",
                        help="files or directories (default: sentio_tpu/)")
    parser.add_argument("--baseline", default=str(DEFAULT_BASELINE),
                        help="baseline JSON (default: analysis/baseline.json)")
    parser.add_argument("--update-baseline", action="store_true",
                        help="re-record the baseline from current findings "
                             "(prunes stale entries)")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="machine-readable output")
    parser.add_argument("--lock-graph", action="store_true",
                        dest="lock_graph",
                        help="dump the static lock-order digraph (nodes, "
                             "acquisition edges with sites, cycles) as "
                             "JSON and exit")
    parser.add_argument("--failures", action="store_true",
                        help="report only the failure-surface rules "
                             "(untyped-boundary-escape, typed rethrow, "
                             "broad swallow, codec/frame contracts)")
    parser.add_argument("--boundary-graph", action="store_true",
                        dest="boundary_graph",
                        help="dump the failure-surface graph (serving "
                             "boundaries with reachable exception escapes, "
                             "frame channels with emit/dispatch sets) as "
                             "JSON and exit")
    parser.add_argument("--sarif", metavar="PATH",
                        help="also write the gate result as SARIF 2.1.0 "
                             "to PATH (new findings = error, baselined = "
                             "note)")
    args = parser.parse_args(argv)

    if args.lock_graph:
        files, _errs = parse_paths(args.paths or [PACKAGE_ROOT])
        graph = build_lock_graph(build_program(files))
        payload = graph.to_json()
        print(json.dumps(payload, indent=1))
        return 0 if not payload["cycles"] else 1

    if args.boundary_graph:
        files, _errs = parse_paths(args.paths or [PACKAGE_ROOT])
        payload = build_failure_graph(build_program(files))
        print(json.dumps(payload, indent=1))
        return 0

    only_rules = set(FAILURE_RULE_IDS) if args.failures else None
    result = run_gate(args.paths or None, baseline_path=args.baseline,
                      only_rules=only_rules)

    if args.update_baseline:
        if args.paths or args.failures:
            # a partial lint (subset of paths OR of rules) sees only a
            # subset of findings; rewriting the baseline from it would
            # silently drop every entry belonging to an unlinted file or
            # rule and break the next full gate
            print("--update-baseline requires a full-tree, all-rules run "
                  "(drop the explicit paths / --failures)", file=sys.stderr)
            return 2
        save_baseline(args.baseline, result.findings,
                      keep_why_from=load_baseline(args.baseline))
        print(f"baseline rewritten: {len(result.findings)} entries "
              f"-> {args.baseline}", file=sys.stderr)
        return 0

    if args.sarif:
        from sentio_tpu.analysis.sarif import to_sarif

        log = to_sarif(result, RULE_IDS, load_baseline(args.baseline))
        Path(args.sarif).write_text(json.dumps(log, indent=1) + "\n")
        print(f"sarif written: {len(result.new) + len(result.matched)} "
              f"results -> {args.sarif}", file=sys.stderr)

    if args.as_json:
        print(json.dumps({
            "ok": result.ok,
            "rules": list(RULE_IDS),
            "new": [dict(f.to_json(), line=f.line) for f in result.new],
            "baselined": [dict(f.to_json(), line=f.line)
                          for f in result.matched],
            "stale": result.stale,
        }, indent=1))
    else:
        for f in result.new:
            print(f"NEW  {f.render()}")
        for f in result.matched:
            print(f"base {f.render()}")
        for e in result.stale:
            print(f"stale baseline entry (fixed? run --update-baseline): "
                  f"{e['path']} [{e['rule']}] {e.get('context', '')}")
        print(result.summary())
    return 0 if result.ok else 1


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(main())
