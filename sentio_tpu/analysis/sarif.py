"""SARIF 2.1.0 export for ``sentio lint`` (``--sarif out.sarif``).

One run, one driver ("sentio-lint"), one result per finding. New findings
map to SARIF level ``error`` (they fail the gate); baselined findings ship
as ``note`` with their justification in the message so code-scanning UIs
show the triage, not just the hit. Stale baseline entries are omitted —
they describe findings that no longer exist.
"""

from __future__ import annotations

from typing import Iterable

__all__ = ["to_sarif"]

_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
           "Schemata/sarif-schema-2.1.0.json")


def _result(finding, level: str, justification: str = "") -> dict:
    text = finding.message
    if justification:
        text += f" [baselined: {justification}]"
    return {
        "ruleId": finding.rule,
        "level": level,
        "message": {"text": text},
        "locations": [{
            "physicalLocation": {
                "artifactLocation": {"uri": finding.path},
                "region": {"startLine": max(finding.line, 1)},
            },
        }],
        "partialFingerprints": {
            # the baseline key: stable across unrelated edits above the line
            "sentioLintKey/v1": f"{finding.rule}|{finding.path}|{finding.context}",
        },
    }


def to_sarif(result, rule_ids: Iterable[str],
             baseline_entries: Iterable[dict] = ()) -> dict:
    """Convert a :class:`~.runner.GateResult` to a SARIF 2.1.0 log dict."""
    why_by_key = {
        (e.get("rule"), e.get("path"), e.get("context", "")): e.get("why", "")
        for e in baseline_entries
    }
    results = [_result(f, "error") for f in result.new]
    results += [
        _result(f, "note", why_by_key.get(f.key, ""))
        for f in result.matched
    ]
    return {
        "$schema": _SCHEMA,
        "version": "2.1.0",
        "runs": [{
            "tool": {
                "driver": {
                    "name": "sentio-lint",
                    "informationUri":
                        "https://github.com/chernistry/sentio",
                    "rules": [{"id": rid} for rid in rule_ids],
                },
            },
            "results": results,
        }],
    }
