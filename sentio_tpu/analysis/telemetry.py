"""Telemetry label-cardinality discipline: no request-derived label values.

The metrics layer keeps every label space BOUNDED by construction — phase
names come from the fixed ``TICK_PHASES`` tuple, shed reasons from typed
enums, replica ids from the configured replica count. One call site that
threads a request-derived value (tenant name, request id, prompt text)
into a ``record_*`` label breaks that globally: series cardinality then
grows with traffic, the Prometheus scrape bloats without bound, and the
fleet-merge path (``merge_worker_series``) faithfully ships the explosion
from every worker to the router. The merge layer has a runtime cardinality
guard (``MAX_WORKER_SERIES_PER_REPLICA``) that caps the damage — this rule
catches the mistake at review time, before a guard has to drop data.

``telemetry-unbounded-labels``
    A ``<obj>.record_*(...)`` / ``<obj>.merge_worker_series(...)`` /
    ``<obj>.set_replica_stat(...)`` call where some argument's value
    derives from a request-scoped identifier: a name/attribute/subscript/
    f-string whose terminal identifier is one of the SUSPECT set
    (``tenant``, ``request_id``, ``prompt``, ...). Recorders that are
    bounded by design are exempt: flight-recorder ``record_tick`` (a
    deque, not a label space) and the tenant-fairness pair
    ``record_tenant_admitted``/``record_tenant_shed`` (the tenant gauge
    set is capped by ``TenantFairQueue.MAX_TRACKED`` eviction).

Suppression: the standard inline ``# lint: allow(<rule>)`` marker for
call sites that bound the value some other way.
"""

from __future__ import annotations

import ast

from sentio_tpu.analysis.findings import Finding, SourceFile

__all__ = ["check_telemetry"]

RULE_UNBOUNDED = "telemetry-unbounded-labels"

# request-scoped identifiers: any of these feeding a metric label value
# makes series cardinality a function of traffic, not configuration
_SUSPECT = frozenset({
    "tenant", "tenant_id", "request_id", "req_id", "rid", "query_id",
    "ticket_id", "session_id", "prompt", "question", "query_text",
    "user", "user_id", "api_key",
})

# bounded-by-design recorders (see module docstring)
_EXEMPT = frozenset({
    "record_tick", "record_tenant_admitted", "record_tenant_shed",
})


def _is_telemetry_call(func: ast.expr) -> bool:
    if not isinstance(func, ast.Attribute):
        return False
    name = func.attr
    if name in _EXEMPT:
        return False
    return (name.startswith("record_")
            or name in ("merge_worker_series", "set_replica_stat"))


def _suspect_in(expr: ast.expr) -> str:
    """First SUSPECT identifier reachable inside ``expr`` (names, attribute
    terminals, constant subscript keys, f-string parts), or ''."""
    for node in ast.walk(expr):
        if isinstance(node, ast.Name) and node.id in _SUSPECT:
            return node.id
        if isinstance(node, ast.Attribute) and node.attr in _SUSPECT:
            return node.attr
        if (isinstance(node, ast.Subscript)
                and isinstance(node.slice, ast.Constant)
                and node.slice.value in _SUSPECT):
            return str(node.slice.value)
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "get" and node.args
                and isinstance(node.args[0], ast.Constant)
                and node.args[0].value in _SUSPECT):
            return str(node.args[0].value)
    return ""


def check_telemetry(tree: ast.Module, src: SourceFile) -> list[Finding]:
    findings: list[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or not _is_telemetry_call(node.func):
            continue
        args = list(node.args) + [kw.value for kw in node.keywords]
        for arg in args:
            suspect = _suspect_in(arg)
            if not suspect:
                continue
            f = src.finding(
                RULE_UNBOUNDED, node.lineno,
                f"telemetry call {node.func.attr}(...) takes a value "
                f"derived from request-scoped {suspect!r} — label "
                f"cardinality would grow with traffic (and the fleet merge "
                f"ships it from every worker); use a bounded enum/bucket, "
                f"or suppress if the value is capped elsewhere",
            )
            if f is not None:
                findings.append(f)
            break  # one finding per call site
    return findings
