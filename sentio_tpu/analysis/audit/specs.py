"""Audit targets: tiny-config engines + per-family variant lowering.

The audit builds REAL engines at micro scale (1-layer, dim-16 model on
CPU), asks each for its declared compile-variant space
(``compile_variant_space()`` — derived from the same ``bucket_size`` /
``_prefill_width`` / ``_prior_bucket`` / tick-ladder helpers the serving
paths call), and abstractly lowers every declared variant through the
engine's OWN jitted functions. Nothing here re-implements a signature: the
args handed to ``.lower()`` are the engine's live state arrays plus
host-numpy call args shaped exactly like ``_dispatch_tick`` /
``_prefill_chunk`` / ``generate`` would shape them.

Variant-space honesty notes:

* the spaces scale with engine config — the micro configs here keep the
  tier-1 lowering count at ~100; a production-config audit enumerates the
  production bucket sets with the same code;
* ``speculative.spec_generate`` (the contiguous fallback path) shares its
  batch/width/window axes with ``engine.generate_fused`` (audited there);
  its variants here sweep the static axes (steps x sampled) at one
  representative shape point;
* mesh variants lower the same families with 2-device tp-sharded state and
  record the ``mhlo.sharding`` argument signatures; the live params/pool
  sharding specs land in the report's ``sharding`` section.
"""

from __future__ import annotations

from typing import Optional

__all__ = ["build_audit_report", "MICRO_VOCAB"]

MICRO_VOCAB = 320  # ByteTokenizer floor is 261


def _micro_cfg():
    from sentio_tpu.models.llama import LlamaConfig

    return LlamaConfig(
        vocab_size=MICRO_VOCAB, dim=16, n_layers=1, n_heads=2, n_kv_heads=2,
        mlp_dim=32, max_len=64, rope_theta=10_000.0,
    )


def _micro_draft_cfg():
    from sentio_tpu.models.llama import LlamaConfig

    return LlamaConfig(
        vocab_size=MICRO_VOCAB, dim=8, n_layers=1, n_heads=2, n_kv_heads=2,
        mlp_dim=16, max_len=64, rope_theta=10_000.0,
    )


def _variant_key(desc: dict) -> str:
    return "|".join(f"{k}={desc[k]}" for k in sorted(desc))


# ------------------------------------------------------------------- engines


def _paged_engine(prefill_chunk: Optional[int] = 8, draft: bool = False,
                  kv_quant: str = "none"):
    import jax

    from sentio_tpu.models.llama import init_llama
    from sentio_tpu.runtime.paged import ContinuousBatchingEngine

    kwargs: dict = {}
    if draft:
        dcfg = _micro_draft_cfg()
        kwargs.update(
            draft_params=init_llama(jax.random.PRNGKey(7), dcfg),
            draft_config=dcfg, spec_k=2, prefill_chunk=None,
        )
    else:
        kwargs.update(prefill_chunk=prefill_chunk)
    return ContinuousBatchingEngine(
        model_config=_micro_cfg(), max_slots=2, page_size=8,
        max_pages_per_seq=4, steps_per_tick=4, max_tick_steps=8,
        use_pallas=False, kv_quant=kv_quant, **kwargs,
    )


def _generator_engine(mesh=None):
    from sentio_tpu.config import GeneratorConfig
    from sentio_tpu.runtime.engine import GeneratorEngine

    eng = GeneratorEngine(
        config=GeneratorConfig(
            provider="tpu", model_preset="tiny",
            max_prompt_tokens=24, max_new_tokens=8,
        ),
        model_config=_micro_cfg(), mesh=mesh,
    )
    # instance-level bucket sets: the audit engine's variant space is the
    # product of these, and lowering ~100 variants must stay inside a
    # tier-1 budget. compile_variant_space()/_encode_batch/_stable_steps
    # all read self.*, so the instance stays self-consistent — a
    # production-config audit simply skips these overrides.
    eng.BATCH_BUCKETS = (1, 4)
    eng.STEP_BUCKETS = (1, 8, 32)
    return eng


# ------------------------------------------------------- per-family lowering


def _paged_args(eng, family: str, desc: dict):
    """(args, static_kwargs) for one paged-engine variant descriptor —
    shaped exactly like the engine's own dispatch sites shape them."""
    import numpy as np

    S = eng.max_slots
    page = eng.page_size

    def prefill_common(rows: int, width: int):
        ids = np.full((rows, width), eng.tokenizer.pad_id, np.int32)
        lens = np.ones(rows, np.int32)
        temps = np.zeros(rows, np.float32)
        scat = np.zeros((rows, width // page), np.int32)
        positions = np.zeros((rows, width), np.int32)
        return ids, positions, lens, temps, scat

    if family == "paged.step_n":
        return (
            (eng.params, np.zeros(S, np.int32), np.zeros(S, np.int32),
             np.zeros(S, bool), eng._page_table.copy(), eng.pool.k,
             eng.pool.v, eng._rng, np.zeros(S, np.float32),
             np.zeros(S, np.int32), np.zeros(S, np.int32),
             # per-slot running logprob accumulators (sum / min / count) —
             # traced [S] data like temps/budgets, no new variant axis
             np.zeros(S, np.float32), np.zeros(S, np.float32),
             np.zeros(S, np.int32)),
            {"steps": desc["steps"]},
        )
    if family == "paged.merge_admitted":
        r = desc["rows"]
        return (
            (np.zeros(S, np.int32), np.zeros(S, np.int32), np.zeros(S, bool),
             np.zeros(S, np.float32), np.zeros(S, np.float32),
             np.zeros(S, np.int32),
             np.zeros(r, np.int32), np.zeros(r, np.float32),
             np.zeros(r, np.int32), np.full(r, S, np.int32)),
            {},
        )
    if family == "paged.prefill_scatter":
        ids, positions, lens, temps, scat = prefill_common(
            desc["rows"], desc["width"])
        return (
            (eng.params, ids, positions, lens, eng._rng, temps, scat,
             eng.pool.k, eng.pool.v, np.zeros(desc["rows"], np.int32)),
            {},
        )
    if family == "paged.prior_prefill_scatter":
        rows = desc["rows"]
        ids, positions, lens, temps, scat = prefill_common(
            rows, desc["width"])
        prior = np.zeros((rows, desc["pnb"]), np.int32)
        n_prior = np.zeros(rows, np.int32)
        return (
            (eng.params, ids, positions, lens, eng._rng, temps, scat,
             eng.pool.k, eng.pool.v, prior, n_prior,
             np.zeros(rows, np.int32)),
            {"do_sample": desc["do_sample"]},
        )
    if family == "paged.draft_prefill":
        eng._ensure_draft_cache()
        rows = desc["rows"]
        ids = np.full((rows, desc["width"]), eng.tokenizer.pad_id, np.int32)
        return (
            (eng.draft_params, ids, eng._spec_dk, eng._spec_dv,
             np.full(rows, S, np.int32), np.ones(rows, np.int32)),
            {},
        )
    if family == "paged_spec.spec_tick":
        eng._ensure_draft_cache()
        steps = desc["steps"]
        return (
            (eng.params, eng.draft_params, np.zeros(S, np.int32),
             np.zeros(S, np.int32), np.zeros(S, bool),
             eng._page_table.copy(), eng.pool.k, eng.pool.v, eng._spec_dk,
             eng._spec_dv, eng._rng, np.zeros(S, np.float32),
             np.zeros(S, np.int32)),
            {"k": eng.spec_k, "out_w": steps + eng.spec_k + 1},
        )
    raise KeyError(f"no arg builder for paged family {family!r}")


def _paged_fn(eng, family: str):
    return {
        "paged.step_n": eng._step_n,
        "paged.merge_admitted": eng._merge_admitted,
        "paged.prefill_scatter": eng._prefill_scatter,
        "paged.prior_prefill_scatter": eng._prior_prefill_scatter,
        "paged.draft_prefill": getattr(eng, "_draft_prefill", None),
        "paged_spec.spec_tick": eng._spec_tick,
    }[family]


def _generator_args(eng, family: str, desc: dict):
    import numpy as np

    from sentio_tpu.models.llama import init_cache

    cfg = eng.model_config
    rows = desc["rows"]
    window = desc["window"]
    cache = init_cache(cfg, rows, window)

    def ids_pos_mask(width: int):
        ids = np.full((rows, width), eng.tokenizer.pad_id, np.int32)
        positions = np.zeros((rows, width), np.int32)
        pad_mask = np.zeros((rows, width), bool)
        return ids, positions, pad_mask

    if family == "engine.prefill":
        ids, positions, pad_mask = ids_pos_mask(desc["width"])
        return (eng.params, ids, positions, cache, pad_mask), {}
    if family == "engine.decode_step":
        return (
            (eng.params, np.zeros((rows, 1), np.int32),
             np.zeros(rows, np.int32), cache, eng._rng, np.float32(0.0),
             np.int32(0)),
            {},
        )
    if family == "engine.generate_fused":
        ids, positions, pad_mask = ids_pos_mask(desc["width"])
        return (
            (eng.params, ids, positions, np.ones(rows, np.int32), cache,
             eng._rng, np.float32(0.0)),
            {"steps": desc["steps"], "top_k": np.int32(0),
             "eos_id": eng.tokenizer.eos_id, "pad_mask": pad_mask},
        )
    raise KeyError(f"no arg builder for generator family {family!r}")


def _generator_fn(eng, family: str):
    return {
        "engine.prefill": eng._prefill,
        "engine.decode_step": eng._decode_step,
        "engine.generate_fused": eng._generate_fused,
    }[family]


# --------------------------------------------------------------- the report


def _audit_family(name, fn, variants, arg_builder) -> dict:
    from sentio_tpu.analysis.audit.lowering import audit_variant
    from sentio_tpu.analysis.audit.registry import get_family

    fam = get_family(name)
    donate = fam.donate_argnums if fam is not None else ()
    statics = fam.static_argnames if fam is not None else ()
    entry: dict = {
        "static_argnames": list(statics),
        "donate_argnums": list(donate),
        "variant_count": len(variants),
        "variants": {},
    }
    for desc in variants:
        args, static_kwargs = arg_builder(desc)
        entry["variants"][_variant_key(desc)] = audit_variant(
            fn, donate, args, static_kwargs
        )
    return entry


def _sharding_section(mesh) -> dict:
    """Live-array sharding specs for the hot-path state: params leaves and
    the paged KV pool. A leaf whose spec string changes (e.g. silently
    replicating a tp-sharded weight) fails the manifest diff."""
    import jax

    out: dict = {}
    gen = _generator_engine(mesh=mesh)
    for path, leaf in jax.tree_util.tree_flatten_with_path(gen.params)[0]:
        key = "params" + jax.tree_util.keystr(path)
        sharding = getattr(leaf, "sharding", None)
        spec = getattr(sharding, "spec", None)
        out[key] = str(spec)

    from sentio_tpu.runtime.paged import ContinuousBatchingEngine

    paged = ContinuousBatchingEngine(
        model_config=gen.model_config, params=gen.params,
        tokenizer=gen.tokenizer, max_slots=2, page_size=8,
        max_pages_per_seq=4, mesh=mesh, use_pallas=False,
    )
    out["paged.pool.k"] = str(paged.pool.k.sharding.spec)
    out["paged.pool.v"] = str(paged.pool.v.sharding.spec)

    # mesh lowerings: the mhlo.sharding argument signature of the two
    # hottest families — replication creep inside the COMPILED artifact
    from sentio_tpu.analysis.audit.lowering import audit_variant

    mesh_variants: dict = {}
    steps = min(paged.tick_step_sizes())
    args, statics = _paged_args(paged, "paged.step_n", {"steps": steps})
    mesh_variants["paged.step_n"] = dict(
        audit_variant(paged._step_n, (5, 6), args, statics,
                      collect_shardings=True),
        variant=f"steps={steps}",
    )
    ids, positions, lens, cache, _n, window, pad_mask = gen._encode_batch(
        ["warm"], 4)
    low = audit_variant(
        gen._prefill, (), (gen.params, ids, positions, cache, pad_mask), {},
        collect_shardings=True,
    )
    mesh_variants["engine.prefill"] = dict(low, variant=f"window={window}")
    return {"state": out, "lowered": mesh_variants}


def build_audit_report(include_mesh: bool = True) -> dict:
    """Build every audit engine, lower every declared variant, and return
    the manifest-shaped report dict."""
    import jax

    from sentio_tpu.models.llama import init_llama, llama_forward, llama_loss

    report: dict = {"version": 1, "families": {}, "sharding": None}

    plain = _paged_engine(prefill_chunk=8)
    plain_space = plain.compile_variant_space()
    for name in ("paged.step_n", "paged.merge_admitted",
                 "paged.prefill_scatter", "paged.prior_prefill_scatter"):
        report["families"][name] = _audit_family(
            name, _paged_fn(plain, name), plain_space[name],
            lambda desc, _n=name: _paged_args(plain, _n, desc),
        )

    # kv_quant="int8": the SAME jit families lower over the {"q","s"} pool
    # pytree — audited as separate manifest entries (name@int8) so the
    # quantized variant space, its donation aliasing (the dict pool still
    # updates in place) and its static footprint are each gated on their
    # own. merge_admitted never touches the pool and needs no second entry.
    quant = _paged_engine(prefill_chunk=None, kv_quant="int8")
    quant_space = quant.compile_variant_space()
    for name in ("paged.step_n", "paged.prefill_scatter",
                 "paged.prior_prefill_scatter"):
        report["families"][name + "@int8"] = _audit_family(
            name, _paged_fn(quant, name), quant_space[name],
            lambda desc, _n=name: _paged_args(quant, _n, desc),
        )

    # the committed footprint claim: int8 pages + f16 per-vector scales vs
    # bf16 pages at identical pool geometry. Measured at a SERVING head_dim
    # (64 — the llama/GQA families this engine serves), not the dim-16
    # lowering micro-config: per-vector scale overhead is 2/head_dim bytes,
    # so head_dim 8 would overstate it 8x. tests/test_audit.py gates the
    # <= 0.6x ratio against both this report and the committed manifest.
    from sentio_tpu.models.llama import LlamaConfig
    from sentio_tpu.runtime.paged import init_pool

    pool_cfg = LlamaConfig(
        vocab_size=MICRO_VOCAB, dim=512, n_layers=2, n_heads=8,
        n_kv_heads=2, mlp_dim=64, max_len=64, rope_theta=10_000.0,
    )
    bf16_pool = init_pool(pool_cfg, num_pages=64, page_size=16)
    int8_pool = init_pool(pool_cfg, num_pages=64, page_size=16,
                          quantized=True)
    report["pools"] = {
        "head_dim": pool_cfg.head_dim,
        "bf16_pool_bytes": bf16_pool.hbm_bytes,
        "int8_pool_bytes": int8_pool.hbm_bytes,
        "ratio": round(int8_pool.hbm_bytes / bf16_pool.hbm_bytes, 4),
    }

    spec = _paged_engine(draft=True)
    spec_space = spec.compile_variant_space()
    for name in ("paged.draft_prefill", "paged_spec.spec_tick"):
        report["families"][name] = _audit_family(
            name, _paged_fn(spec, name), spec_space[name],
            lambda desc, _n=name: _paged_args(spec, _n, desc),
        )

    gen = _generator_engine()
    gen_space = gen.compile_variant_space()
    for name in ("engine.prefill", "engine.decode_step",
                 "engine.generate_fused"):
        report["families"][name] = _audit_family(
            name, _generator_fn(gen, name), gen_space[name],
            lambda desc, _n=name: _generator_args(gen, _n, desc),
        )

    # contiguous speculative fallback: static axes at one shape point (the
    # batch/width/window axes are the generator's, audited above)
    from sentio_tpu.models.llama import init_cache
    from sentio_tpu.runtime.speculative import build_spec_generate

    import numpy as np

    cfg, dcfg = gen.model_config, _micro_draft_cfg()
    spec_fn = build_spec_generate(
        llama_forward, cfg, llama_forward, dcfg,
        eos_id=gen.tokenizer.eos_id, attn_fn=None,
    )
    draft_params = init_llama(jax.random.PRNGKey(11), dcfg)
    spec_k = 2
    rows, width, window = 1, 32, 64
    steps_set = [b for b in gen.STEP_BUCKETS if b <= cfg.max_len - 1]

    def spec_args(desc):
        ids = np.full((rows, width), gen.tokenizer.pad_id, np.int32)
        return (
            (gen.params, draft_params, ids, np.zeros((rows, width), np.int32),
             np.ones(rows, np.int32), init_cache(cfg, rows, window),
             init_cache(dcfg, rows, window)),
            {"steps": desc["steps"], "k": spec_k,
             "pad_mask": np.zeros((rows, width), bool), "rng": gen._rng,
             "temperature": np.float32(0.0), "sampled": desc["sampled"]},
        )

    report["families"]["speculative.spec_generate"] = _audit_family(
        "speculative.spec_generate", spec_fn,
        [{"steps": s, "sampled": smp}
         for s in steps_set for smp in (False, True)],
        spec_args,
    )

    # training objective (multi-chip dry-run train step): one canonical shape
    def loss_args(desc):
        b, t = desc["b"], desc["t"]
        return (
            (gen.params, cfg, np.zeros((b, t + 1), np.int32),
             np.ones((b, t + 1), np.int32)),
            {},
        )

    report["families"]["llama.loss"] = _audit_family(
        "llama.loss", llama_loss, [{"b": 2, "t": 16}], loss_args,
    )

    if include_mesh and len(jax.devices()) >= 2:
        from sentio_tpu.config import MeshConfig
        from sentio_tpu.parallel.mesh import build_mesh

        mesh = build_mesh(MeshConfig(dp_size=1, tp_size=2),
                          devices=jax.devices()[:2])
        report["sharding"] = _sharding_section(mesh)
    return report
