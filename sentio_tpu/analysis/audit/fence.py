"""Compile fence: post-warmup recompiles become hard, attributable errors.

The engine's latency story assumes every compiled program exists before
traffic arrives; a mid-serving XLA compile is a multi-second stall that
tail latencies cannot hide. The fence makes that class of regression LOUD:

* every compile observed at a registered ``jit_family`` site is counted
  here (per-family totals + a bounded recent-event ring), feeding the
  ``sentio_tpu_xla_compiles_total`` counter, the flight recorder's per-tick
  ``xla_compiles`` field, and bench.py's phase-A compile count;
* with ``SENTIO_COMPILE_FENCE=1``, serving/bench warmup ends with
  :func:`arm` — any LATER compile raises :class:`CompileFenceError`
  carrying the offending family and the abstract signature that compiled.

Arming is strict by design: it is a canary/CI mode for deployments whose
warmup sweeps the traffic shapes they serve (see
``PagedGenerationService.warmup``). A fence error in production means
either warmup coverage or the committed compile manifest is wrong — both
are findings, not noise.
"""

from __future__ import annotations

import os
import threading
from collections import deque

__all__ = [
    "CompileFenceError",
    "enabled",
    "arm",
    "disarm",
    "is_armed",
    "note_compile",
    "compiles_total",
    "per_family_totals",
    "drain_events",
    "reset",
]

_lock = threading.Lock()
_totals: dict[str, int] = {}  # guarded-by: _lock
_events: deque = deque(maxlen=256)  # guarded-by: _lock
_armed = False  # guarded-by: _lock


class CompileFenceError(RuntimeError):
    """A registered jit family compiled AFTER the fence was armed."""

    def __init__(self, family: str, signature: str) -> None:
        self.family = family
        self.signature = signature
        super().__init__(
            f"compile fence: post-warmup XLA compile at family "
            f"{family!r} for signature {signature} — warm this variant "
            f"before arming, or treat it as a recompile regression"
        )


def enabled() -> bool:
    """``SENTIO_COMPILE_FENCE=1`` (read per call: tests flip it)."""
    return os.environ.get("SENTIO_COMPILE_FENCE", "") == "1"


def arm() -> None:
    """Declare warmup over: later compiles at registered families raise."""
    global _armed
    with _lock:
        _armed = True


def disarm() -> None:
    global _armed
    with _lock:
        _armed = False


def is_armed() -> bool:
    with _lock:
        return _armed


def reset() -> None:
    """Zero all counters and disarm (test isolation)."""
    global _armed
    with _lock:
        _totals.clear()
        _events.clear()
        _armed = False


def note_compile(family: str, signature: str, n: int = 1,
                 exempt: bool = False) -> None:
    """Record ``n`` compiles at ``family`` (called by ``FamilyFn`` on jit
    cache growth). Raises :class:`CompileFenceError` when armed — unless
    ``exempt`` (a supervised replica rebuild marks the NEW engine's
    FamilyFn instances exempt for the duration of its warmup, so its cold
    compiles pass while a steady-state recompile on any OTHER engine still
    trips the fence). Exempt compiles are still counted and evented."""
    with _lock:
        _totals[family] = _totals.get(family, 0) + n
        _events.append({"family": family, "signature": signature, "n": n})
        armed = _armed and not exempt
    try:  # telemetry is best-effort; the counter must never break a tick
        from sentio_tpu.infra.metrics import get_metrics

        get_metrics().record_compiles(family, n)
    except Exception:  # noqa: BLE001 — compile-counter telemetry must never break a fence tick
        pass
    if armed:
        raise CompileFenceError(family, signature)


def compiles_total() -> int:
    with _lock:
        return sum(_totals.values())


def per_family_totals() -> dict[str, int]:
    with _lock:
        return dict(_totals)


def drain_events() -> list[dict]:
    """Pop-and-return the recent compile events (single consumer: the
    decode pump folds them into flight-recorder ticks)."""
    with _lock:
        out = list(_events)
        _events.clear()
    return out
