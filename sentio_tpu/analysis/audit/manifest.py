"""Compile-manifest IO + ratchet diff.

Same gate semantics as the lint baseline (analysis/findings.py): the
committed ``analysis/compile_manifest.json`` is the promise, the fresh
audit report is the reality, and only REGRESSIONS fail —

* a family or variant that exists now but not in the manifest (the compile
  space grew),
* a changed static/donate contract,
* a donated buffer that lowering no longer aliases,
* static HBM footprint growth on any variant,
* a sharding-spec change on any hot-path array or lowered signature.

Improvements (variant removed, donation gained, footprint shrunk) report as
STALE — the run stays green but nags for ``--update-manifest``, so the
manifest only drifts when a human re-records it deliberately. ``info``
fields (flops / bytes accessed) are never gated: they are XLA facts, not
contracts.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from pathlib import Path
from typing import Optional

__all__ = [
    "DEFAULT_MANIFEST",
    "AuditDiff",
    "load_manifest",
    "save_manifest",
    "diff_manifest",
]

DEFAULT_MANIFEST = Path(__file__).resolve().parents[1] / "compile_manifest.json"


def load_manifest(path: str | Path) -> Optional[dict]:
    p = Path(path)
    if not p.exists():
        return None
    data = json.loads(p.read_text())
    if not isinstance(data, dict):
        raise ValueError(f"manifest {p} must be a JSON object")
    return data


def save_manifest(path: str | Path, report: dict) -> None:
    Path(path).write_text(
        json.dumps(report, indent=1, sort_keys=True) + "\n"
    )


@dataclass
class AuditDiff:
    regressions: list[dict] = field(default_factory=list)
    stale: list[dict] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.regressions

    def summary(self) -> str:
        return (
            f"{len(self.regressions)} regressions, "
            f"{len(self.stale)} stale manifest entries"
        )


def _fail(diff: AuditDiff, kind: str, where: str, detail: str) -> None:
    diff.regressions.append({"kind": kind, "where": where, "detail": detail})


def _stale(diff: AuditDiff, kind: str, where: str, detail: str) -> None:
    diff.stale.append({"kind": kind, "where": where, "detail": detail})


def _diff_variant(diff: AuditDiff, where: str, cur: dict, man: dict) -> None:
    if cur.get("aliased", 0) < man.get("aliased", 0):
        _fail(diff, "donation-dropped", where,
              f"lowering aliases {cur.get('aliased', 0)} donated leaves, "
              f"manifest promises {man.get('aliased', 0)}")
    elif (cur.get("aliased"), cur.get("donated_leaves")) != (
            man.get("aliased"), man.get("donated_leaves")):
        _stale(diff, "donation-changed", where,
               f"{man.get('aliased')}/{man.get('donated_leaves')} -> "
               f"{cur.get('aliased')}/{cur.get('donated_leaves')}")
    cur_hbm = cur.get("arg_bytes", 0) + cur.get("out_bytes", 0)
    man_hbm = man.get("arg_bytes", 0) + man.get("out_bytes", 0)
    if cur_hbm > man_hbm:
        _fail(diff, "hbm-growth", where,
              f"static footprint {man_hbm} -> {cur_hbm} bytes")
    elif cur_hbm < man_hbm:
        _stale(diff, "hbm-shrunk", where, f"{man_hbm} -> {cur_hbm} bytes")
    if cur.get("arg_shardings") != man.get("arg_shardings"):
        _fail(diff, "sharding-drift", where,
              f"lowered arg shardings {man.get('arg_shardings')} -> "
              f"{cur.get('arg_shardings')}")


def _diff_family(diff: AuditDiff, name: str, cur: dict, man: dict) -> None:
    for key in ("static_argnames", "donate_argnums"):
        if list(cur.get(key, [])) != list(man.get(key, [])):
            _fail(diff, "contract-changed", name,
                  f"{key}: {man.get(key)} -> {cur.get(key)}")
    cur_v, man_v = cur.get("variants", {}), man.get("variants", {})
    for vkey in sorted(set(cur_v) - set(man_v)):
        _fail(diff, "new-variant", f"{name}[{vkey}]",
              "compile variant not in manifest — the variant space grew")
    for vkey in sorted(set(man_v) - set(cur_v)):
        _stale(diff, "variant-removed", f"{name}[{vkey}]",
               "manifest variant no longer declared")
    for vkey in sorted(set(cur_v) & set(man_v)):
        _diff_variant(diff, f"{name}[{vkey}]", cur_v[vkey], man_v[vkey])


def _diff_sharding(diff: AuditDiff, cur: Optional[dict],
                   man: Optional[dict]) -> None:
    if man is None and cur is None:
        return
    if cur is None:
        _stale(diff, "sharding-unavailable", "sharding",
               "report built with < 2 devices; mesh section not audited")
        return
    if man is None:
        _fail(diff, "sharding-drift", "sharding",
              "manifest has no sharding section; run --update-manifest")
        return
    for section in ("state", "lowered"):
        cur_s, man_s = cur.get(section, {}), man.get(section, {})
        for key in sorted(set(cur_s) - set(man_s)):
            _fail(diff, "sharding-drift", f"sharding.{section}.{key}",
                  "new sharded array/signature not in manifest")
        for key in sorted(set(man_s) - set(cur_s)):
            _stale(diff, "sharding-removed", f"sharding.{section}.{key}",
                   "manifest entry no longer present")
        for key in sorted(set(cur_s) & set(man_s)):
            if section == "state":
                if cur_s[key] != man_s[key]:
                    _fail(diff, "sharding-drift", f"sharding.state.{key}",
                          f"{man_s[key]} -> {cur_s[key]} (replication creep?)")
            else:
                _diff_variant(diff, f"sharding.lowered.{key}",
                              cur_s[key], man_s[key])


def diff_manifest(report: dict, manifest: Optional[dict]) -> AuditDiff:
    diff = AuditDiff()
    if manifest is None:
        _fail(diff, "no-manifest", "manifest",
              "no committed compile manifest; run "
              "`sentio audit --update-manifest` and commit the result")
        return diff
    cur_f = report.get("families", {})
    man_f = manifest.get("families", {})
    for name in sorted(set(cur_f) - set(man_f)):
        _fail(diff, "new-family", name,
              "jit family not in manifest — new compile surface")
    for name in sorted(set(man_f) - set(cur_f)):
        _stale(diff, "family-removed", name, "manifest family not audited")
    for name in sorted(set(cur_f) & set(man_f)):
        _diff_family(diff, name, cur_f[name], man_f[name])
    _diff_sharding(diff, report.get("sharding"), manifest.get("sharding"))
    return diff
