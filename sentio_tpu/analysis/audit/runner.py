"""``sentio audit`` orchestration: report -> coverage check -> manifest gate.

``run_audit`` builds the tiny-config report (specs.py), verifies every
``jit_family`` registered in this process has an audit spec (a NEW jit site
without one fails — the registry is the discovery mechanism), and diffs
against the committed manifest. ``main`` is the CLI entry point; when it
owns the process (jax not yet imported) it pins the platform to CPU with
two virtual devices so the committed manifest is reproducible on any host.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from dataclasses import dataclass
from typing import Optional

__all__ = ["run_audit", "main", "AuditResult"]


@dataclass
class AuditResult:
    report: dict
    diff: "object"  # AuditDiff

    @property
    def ok(self) -> bool:
        return self.diff.ok

    def variant_count(self) -> int:
        return sum(
            len(f.get("variants", {}))
            for f in self.report.get("families", {}).values()
        )


def _check_coverage(report: dict, diff) -> None:
    """Every family name registered in this process must have been lowered
    by the report — adding a ``jit_family`` site without an audit spec is
    itself a finding. Unregistered test fixtures use ``register=False``."""
    from sentio_tpu.analysis.audit.manifest import _fail
    from sentio_tpu.analysis.audit.registry import families

    audited = set(report.get("families", {}))
    for name in sorted(set(families()) - audited):
        _fail(diff, "family-unaudited", name,
              "jit_family registered but analysis/audit/specs.py has no "
              "variant spec for it")


def run_audit(manifest_path: Optional[str] = None,
              include_mesh: bool = True) -> AuditResult:
    from sentio_tpu.analysis.audit.manifest import (
        DEFAULT_MANIFEST,
        diff_manifest,
        load_manifest,
    )
    from sentio_tpu.analysis.audit.specs import build_audit_report

    report = build_audit_report(include_mesh=include_mesh)
    manifest = load_manifest(manifest_path or DEFAULT_MANIFEST)
    diff = diff_manifest(report, manifest)
    _check_coverage(report, diff)
    return AuditResult(report=report, diff=diff)


def _pin_platform() -> None:
    """CPU + 2 virtual devices, but only when this process has not already
    initialized jax (in-process callers keep their platform)."""
    if "jax" in sys.modules:
        return
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=2"
        ).strip()


def main(argv: Optional[list[str]] = None) -> int:
    from sentio_tpu.analysis.audit.manifest import DEFAULT_MANIFEST

    parser = argparse.ArgumentParser(
        prog="sentio audit",
        description="AOT-lower every registered jit family on a tiny CPU "
                    "config and gate variants/donation/sharding/HBM against "
                    "the committed compile manifest",
    )
    parser.add_argument("--manifest", default=str(DEFAULT_MANIFEST),
                        help="manifest JSON (default: "
                             "analysis/compile_manifest.json)")
    parser.add_argument("--update-manifest", action="store_true",
                        help="re-record the manifest from the current audit")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="machine-readable output")
    parser.add_argument("--no-mesh", action="store_true",
                        help="skip the 2-device sharding section")
    args = parser.parse_args(argv)

    _pin_platform()
    result = run_audit(manifest_path=args.manifest,
                       include_mesh=not args.no_mesh)

    if args.update_manifest:
        from sentio_tpu.analysis.audit.manifest import save_manifest

        save_manifest(args.manifest, result.report)
        print(
            f"manifest rewritten: {len(result.report['families'])} families, "
            f"{result.variant_count()} variants -> {args.manifest}",
            file=sys.stderr,
        )
        return 0

    if args.as_json:
        print(json.dumps({
            "ok": result.ok,
            "families": len(result.report["families"]),
            "variants": result.variant_count(),
            "regressions": result.diff.regressions,
            "stale": result.diff.stale,
        }, indent=1))
    else:
        for r in result.diff.regressions:
            print(f"FAIL  {r['kind']}: {r['where']} — {r['detail']}")
        for s in result.diff.stale:
            print(f"stale {s['kind']}: {s['where']} — {s['detail']} "
                  f"(run --update-manifest)")
        print(
            f"audited {len(result.report['families'])} families / "
            f"{result.variant_count()} variants: {result.diff.summary()}"
        )
    return 0 if result.ok else 1


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(main())
