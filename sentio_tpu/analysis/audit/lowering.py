"""Abstract lowering + property extraction for one compile variant.

``jax.jit(...).lower(*example_args, **statics)`` traces the function over
abstract values — no FLOP executes, no buffer is donated — and yields the
StableHLO module XLA would compile. Three properties gate the manifest:

* **donation aliasing** — jax matches each donated input leaf to an output
  of identical shape/dtype(/sharding) during lowering; a matched leaf gets
  a ``tf.aliasing_output`` argument attribute in the module. Counting those
  attributes against the donated leaf count is the honest "is the pool
  REALLY updated in place" check (paged.py's comment-only contract until
  now). A dropped donation (shape drift, output reorder, dtype mismatch)
  simply loses its attribute — platform-independently, so CPU tier-1 can
  gate TPU-relevant donation behavior.
* **static HBM footprint** — argument/result byte totals computed from the
  avals (pure shape math, deterministic everywhere). Pool growth or an
  accidentally materialized copy shows up here.
* **sharding signatures** — arguments carrying a ``NamedSharding`` lower
  with ``mhlo.sharding`` attributes; the sorted multiset of those strings
  is the replication-creep gate for mesh variants.

FLOPs / bytes-accessed from ``Lowered.cost_analysis()`` are recorded as
``info`` only — useful for eyeballing a diff, excluded from gating (they
are an XLA implementation detail, not a contract).
"""

from __future__ import annotations

import re
from typing import Any

__all__ = ["audit_variant", "lower_variant", "count_aliased", "tree_bytes"]

_ALIAS_RE = re.compile(r"tf\.aliasing_output")
_SHARDING_RE = re.compile(r'mhlo\.sharding = "([^"]*)"')


def tree_bytes(tree: Any) -> int:
    """Total bytes of every array-like leaf (shape x dtype, no device IO)."""
    import jax
    import numpy as np

    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None or dtype is None:
            continue
        total += int(np.prod(shape, dtype=np.int64)) * np.dtype(dtype).itemsize
    return total


def count_aliased(stablehlo_text: str) -> int:
    """Donated input leaves jax actually aliased to an output."""
    main = _main_signature(stablehlo_text)
    return len(_ALIAS_RE.findall(main))


def _main_signature(stablehlo_text: str) -> str:
    """The @main func signature (arg attributes live there; searching the
    whole module would also match nested private funcs). MLIR prints the
    signature — including inline ``{tf.aliasing_output = ...}`` attribute
    dicts — on one line ending with the body-opening brace."""
    marker = "func.func public @main("
    start = stablehlo_text.find(marker)
    if start < 0:
        return stablehlo_text
    end = stablehlo_text.find("\n", start)
    return stablehlo_text[start : end if end > 0 else len(stablehlo_text)]


def donated_leaf_count(donate_argnums: tuple[int, ...], args: tuple) -> int:
    """How many flat array leaves the declared donation covers."""
    import jax

    total = 0
    for i in donate_argnums:
        if i < len(args):
            total += len(jax.tree_util.tree_leaves(args[i]))
    return total


def lower_variant(fn: Any, args: tuple, static_kwargs: dict):
    """AOT-lower one variant. ``fn`` may be a FamilyFn (``.lower`` forwards
    to the jitted inner) or a bare jitted function."""
    return fn.lower(*args, **static_kwargs)


def audit_variant(
    fn: Any,
    donate_argnums: tuple[int, ...],
    args: tuple,
    static_kwargs: dict,
    collect_shardings: bool = False,
) -> dict:
    """Lower one variant and extract its gated properties.

    Returns a manifest-entry dict: ``donated_leaves`` (declared),
    ``aliased`` (what lowering kept), ``arg_bytes``/``out_bytes`` (static
    footprint), optional ``arg_shardings`` (sorted mhlo strings, mesh
    variants only), and non-gated ``info`` (flops / bytes accessed).
    """
    lowered = lower_variant(fn, args, static_kwargs)
    text = lowered.as_text()
    entry: dict = {
        "donated_leaves": donated_leaf_count(donate_argnums, args),
        "aliased": count_aliased(text),
        "arg_bytes": tree_bytes(args),
        "out_bytes": _out_bytes(lowered, fn, args, static_kwargs),
    }
    if collect_shardings:
        entry["arg_shardings"] = sorted(
            _SHARDING_RE.findall(_main_signature(text))
        )
    info: dict = {}
    try:
        cost = lowered.cost_analysis() or {}
        for key in ("flops", "bytes accessed"):
            if key in cost:
                info[key.replace(" ", "_")] = float(cost[key])
    except Exception:  # noqa: BLE001 — cost analysis is backend-optional
        pass
    if info:
        entry["info"] = info
    return entry


def _out_bytes(lowered: Any, fn: Any, args: tuple, static_kwargs: dict) -> int:
    """Output footprint from the lowering's own out avals when the jax
    version exposes them; otherwise one extra abstract trace."""
    import jax

    out_info = getattr(lowered, "out_info", None)
    if out_info is not None:
        return tree_bytes(out_info)
    # fall back to the bare jitted fn (NOT the FamilyFn wrapper — an
    # eval_shape must never feed the compile counters)
    inner = getattr(fn, "_fn", fn)
    return tree_bytes(jax.eval_shape(inner, *args, **static_kwargs))
