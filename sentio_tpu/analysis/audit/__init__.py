"""Compile-manifest audit: AOT accounting for every jitted family.

``sentio lint`` (PR 3) pattern-matches the AST; it cannot see what XLA will
really build. This package closes that gap at the artifact level, the way
production TPU serving stacks gate recompile and donation regressions
before they reach a pod:

* **registry** — ``jit_family(name, ...)`` replaces bare
  ``@partial(jax.jit, ...)`` at every serving-critical jit site. It applies
  ``jax.jit`` itself (single source of truth for static/donated argnums),
  records the family in a process-global registry, and counts XLA cache
  misses per call — the raw signal for both telemetry and the fence.
* **fence** — ``SENTIO_COMPILE_FENCE=1`` + ``fence.arm()`` (after warmup)
  turns any further compile at a registered family into a hard
  ``CompileFenceError`` carrying the family name and the abstract call
  signature. Compile counts/events feed ``sentio_tpu_xla_compiles_total``
  and the flight recorder's per-tick events.
* **specs / lowering** — builds tiny-config engines on CPU, enumerates each
  family's DECLARED variant space (the same ``bucket_size`` /
  ``_prefill_width`` / ``_prior_bucket`` / tick-ladder helpers the runtime
  uses), and abstractly lowers every variant (``.lower()`` on tiny shapes —
  no compute) to extract donation aliasing, static HBM footprint, and mesh
  sharding signatures.
* **manifest / runner** — diffs the report against the committed
  ``analysis/compile_manifest.json`` with the same ratchet semantics as the
  lint baseline: a new variant, a lost donation, HBM growth, or sharding
  drift on a hot-path array fails ``sentio audit`` (and tier-1);
  ``--update-manifest`` re-records honestly.
"""

from sentio_tpu.analysis.audit.registry import jit_family  # noqa: F401

__all__ = ["jit_family"]
