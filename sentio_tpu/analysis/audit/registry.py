"""``jit_family``: the registry decorator behind the compile-manifest audit.

Every serving-critical jit site declares itself once::

    @jit_family("paged.step_n", static_argnames=("steps",),
                donate_argnums=(5, 6))
    def step_n(params, tok, ...):
        ...

The decorator applies ``jax.jit`` itself, so the static/donated argnums it
records are BY CONSTRUCTION the ones XLA sees — there is no second copy to
drift. The returned :class:`FamilyFn` is a thin callable wrapper that:

* forwards calls (and ``.lower`` / ``.clear_cache`` / ``._cache_size`` /
  every other attribute) to the underlying jitted function;
* after each call, compares the jit cache size against the last observed
  value — growth means XLA compiled a new variant — and reports the event
  to :mod:`sentio_tpu.analysis.audit.fence` with the family name and the
  abstract signature of the offending call.

``sentio lint``'s retrace rules recognize ``@jit_family(...)`` exactly like
``@partial(jax.jit, ...)`` (analysis/retrace.py), so moving a site onto the
registry never loses static-arg boundedness coverage.

The registry is process-global and last-wins per name: engines rebuild
their jitted closures per instance (``_build_fns``), and the audit only
needs (a) the full set of family NAMES that exist — its coverage check
fails when a new ``jit_family`` site appears without an audit spec — and
(b) the declared static/donate contract per name.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Any, Optional

__all__ = ["jit_family", "FamilyFn", "JitFamily", "families", "get_family"]


@dataclass
class JitFamily:
    """One registered jit family: the declared compile contract plus the
    most recently constructed jitted instance."""

    name: str
    static_argnames: tuple[str, ...]
    donate_argnums: tuple[int, ...]
    fn: "FamilyFn"


_REGISTRY: dict[str, JitFamily] = {}  # guarded-by: _registry_lock
_registry_lock = threading.Lock()


def families() -> dict[str, JitFamily]:
    """Snapshot of every family registered so far in this process."""
    with _registry_lock:
        return dict(_REGISTRY)


def get_family(name: str) -> Optional[JitFamily]:
    with _registry_lock:
        return _REGISTRY.get(name)


def abstract_signature(args: tuple, kwargs: dict) -> str:
    """Compact dtype[shape] rendering of a call's dynamic arguments — what a
    fence error / compile event reports as "the shape that recompiled"."""
    import jax

    def leaf(x: Any) -> str:
        shape = getattr(x, "shape", None)
        dtype = getattr(x, "dtype", None)
        if shape is not None and dtype is not None:
            return f"{dtype}[{','.join(str(d) for d in shape)}]"
        return repr(x)[:32]

    leaves = [leaf(x) for x in jax.tree_util.tree_leaves((args, kwargs))]
    return "(" + ", ".join(leaves) + ")"


class FamilyFn:
    """Callable wrapper over one jitted function instance. Call overhead is
    one ``_cache_size()`` C++ call per dispatch — noise next to the
    dispatch itself."""

    def __init__(self, family: str, fn: Any) -> None:
        self.family = family
        self._fn = fn
        self._cache_size_fn = getattr(fn, "_cache_size", None)
        self._seen = 0
        # armed-fence bypass for THIS instance only: a supervised replica
        # rebuild sets it while warming its fresh engine (whose FamilyFns
        # are all cold), then clears it — compiles on other instances keep
        # tripping the fence throughout
        self.fence_exempt = False

    def __call__(self, *args, **kwargs):
        out = self._fn(*args, **kwargs)
        if self._cache_size_fn is not None:
            n = self._cache_size_fn()
            if n > self._seen:
                delta = n - self._seen
                self._seen = n
                from sentio_tpu.analysis.audit import fence

                # may raise CompileFenceError when the fence is armed — the
                # compile already happened; the error is the report
                fence.note_compile(
                    self.family, abstract_signature(args, kwargs), delta,
                    exempt=self.fence_exempt,
                )
        return out

    def __getattr__(self, name: str):
        # .lower / .eval_shape / .clear_cache / ._cache_size ... — AOT
        # lowering through this path never touches the compile counters
        return getattr(self._fn, name)

    def __repr__(self) -> str:  # pragma: no cover - debugging nicety
        return f"FamilyFn({self.family!r}, {self._fn!r})"


def jit_family(
    name: str,
    *,
    static_argnames: tuple[str, ...] = (),
    donate_argnums: tuple[int, ...] = (),
    register: bool = True,
):
    """Decorator: ``jax.jit`` + registry entry + compile accounting.

    ``register=False`` builds the counting wrapper without touching the
    process-global registry — for test fixtures that must not make the
    audit's coverage check order-dependent.
    """

    def deco(fn):
        import jax

        jitted = jax.jit(
            fn,
            static_argnames=tuple(static_argnames),
            donate_argnums=tuple(donate_argnums),
        )
        wrapped = FamilyFn(name, jitted)
        if register:
            with _registry_lock:
                _REGISTRY[name] = JitFamily(
                    name=name,
                    static_argnames=tuple(static_argnames),
                    donate_argnums=tuple(donate_argnums),
                    fn=wrapped,
                )
        return wrapped

    return deco
