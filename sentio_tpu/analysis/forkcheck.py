"""Fork hygiene: JAX is not fork-safe — spawn or nothing.

A ``fork()`` duplicates the parent's threads' locks in whatever state they
were in at the instant of the fork — but only the forking thread survives
into the child. JAX's runtime (PJRT client, compilation cache, collective
launchers) is heavily threaded, so a forked child deadlocks on the first
dispatch that touches a lock some dead thread was holding. The process-mode
replica tier (runtime/worker.py) therefore spawns its workers, and this
rule keeps anyone from quietly reintroducing fork semantics anywhere in the
tree:

``no-fork``
    Fires on:

    * ``os.fork()`` / ``os.forkpty()`` (also the from-imported bare names);
    * ``get_context("fork")`` / ``get_context("forkserver")`` and
      ``set_start_method`` with either — a forkserver parent imports jax
      too, so it inherits the same hazard;
    * any ``Process(...)`` / ``Pool(...)`` construction (bare or attribute
      form): on Linux the DEFAULT multiprocessing start method is fork, so
      every worker construction must go through an explicit spawn context
      — and the vetted spawn-context call sites carry the inline
      ``# lint: allow(no-fork)`` marker (runtime/worker.py is the one
      legitimate site today).

Suppression: the standard inline ``# lint: allow(no-fork)`` marker.
"""

from __future__ import annotations

import ast

from sentio_tpu.analysis.findings import Finding, SourceFile

__all__ = ["check_fork"]

RULE = "no-fork"

# direct fork syscall wrappers (attribute or from-imported name form)
_FORK_CALLS = ("fork", "forkpty")

# multiprocessing context selectors whose string argument picks the method
_CONTEXT_CALLS = ("get_context", "set_start_method")

# worker constructions that inherit the platform-DEFAULT start method
# (fork on Linux) unless made from an explicit spawn context
_WORKER_CALLS = ("Process", "Pool")


def _call_name(node: ast.Call) -> str:
    """The trailing name of the called thing: ``obj.attr(...)`` → attr,
    ``name(...)`` → name, anything else → ''."""
    if isinstance(node.func, ast.Attribute):
        return node.func.attr
    if isinstance(node.func, ast.Name):
        return node.func.id
    return ""


def _first_str_arg(node: ast.Call) -> str:
    for arg in list(node.args) + [kw.value for kw in node.keywords]:
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            return arg.value
    return ""


def check_fork(tree: ast.Module, src: SourceFile) -> list[Finding]:
    findings: list[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = _call_name(node)
        f = None
        if name in _FORK_CALLS:
            f = src.finding(
                RULE, node.lineno,
                f"{name}() forks a process whose JAX runtime threads' "
                "locks copy in a held state — the child deadlocks on its "
                "first dispatch; spawn a fresh interpreter instead "
                "(runtime/worker.py)",
            )
        elif name in _CONTEXT_CALLS:
            method = _first_str_arg(node)
            if method.startswith("fork"):
                f = src.finding(
                    RULE, node.lineno,
                    f"{name}({method!r}) selects a fork-based start method "
                    "— JAX is not fork-safe; use get_context(\"spawn\")",
                )
        elif name in _WORKER_CALLS:
            f = src.finding(
                RULE, node.lineno,
                f"{name}(...) without a vetted spawn context: the Linux "
                "default start method is fork, which deadlocks a "
                "JAX-initialized child — construct via "
                "get_context(\"spawn\") and annotate the call site with "
                "`# lint: allow(no-fork)`",
            )
        if f is not None:
            findings.append(f)
    return findings
