"""Lock-discipline checker: guarded-by annotations → lockset verification.

Eraser-style (Savage et al., TOSP 1997) guarded-by discipline, checked
lexically instead of dynamically: an attribute annotated
``# guarded-by: <lock>`` on its initializing assignment may only be touched
from methods of its class while ``with self.<lock>:`` is lexically open.

What counts as "the lock is held":

* the access sits inside a ``with self.<lock>:`` (or ``with self.<lock>``
  among multiple items) block of the same method;
* the method's name ends in ``_locked`` (call-side contract: caller holds
  the lock);
* the method's ``def`` line carries ``# lock-held: <lock>``;
* the method is ``__init__`` / ``__post_init__`` (construction happens
  before the object is shared).

Functions *defined* inside a ``with`` block (lambdas, closures) do NOT
inherit the lock — they run later, on whatever thread calls them; accesses
inside them are checked as unlocked.

The special lock name ``engine-thread`` declares single-driver-thread
ownership instead of a mutex. The static checker records but does not
verify those attributes (thread identity is not lexical); the runtime
sanitizer (:mod:`.sanitizer`) enforces the contract on engine entry points
under ``SENTIO_SANITIZE=1``.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from sentio_tpu.analysis.findings import Finding, SourceFile

__all__ = ["check_locks", "collect_guarded", "THREAD_LOCKS"]

RULE_LOCK = "lock-discipline"

# lock "names" that mean thread ownership, not a mutex — skipped statically
THREAD_LOCKS = {"engine-thread", "pump-thread"}


@dataclass
class GuardedClass:
    name: str
    # attr -> lock attribute name (e.g. "_mutex")
    guarded: dict[str, str] = field(default_factory=dict)
    thread_owned: set[str] = field(default_factory=set)


def collect_guarded(tree: ast.Module, src: SourceFile) -> dict[str, GuardedClass]:
    """Scan every class for ``self.<attr> = ...  # guarded-by: <lock>``
    annotations (searched on the assignment's first and last physical line,
    for multi-line initializers)."""
    out: dict[str, GuardedClass] = {}
    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        gc = GuardedClass(cls.name)
        for node in ast.walk(cls):
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            targets = node.targets if isinstance(node, ast.Assign) else [node.target]
            attrs = [
                t.attr for t in targets
                if isinstance(t, ast.Attribute)
                and isinstance(t.value, ast.Name) and t.value.id == "self"
            ]
            if not attrs:
                continue
            lock = src.guarded_by(node.lineno) or src.guarded_by(
                getattr(node, "end_lineno", node.lineno)
            )
            if lock is None:
                continue
            for attr in attrs:
                if lock in THREAD_LOCKS:
                    gc.thread_owned.add(attr)
                else:
                    gc.guarded[attr] = lock
        if gc.guarded or gc.thread_owned:
            out[cls.name] = gc
    return out


def _with_locks(node: ast.With) -> set[str]:
    """Lock attribute names context-managed by this ``with``."""
    out: set[str] = set()
    for item in node.items:
        expr = item.context_expr
        if (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self"):
            out.add(expr.attr)
    return out


def _method_held_locks(fn: ast.FunctionDef, src: SourceFile) -> set[str]:
    """Locks the whole method body may assume held."""
    held: set[str] = set()
    if fn.name.endswith("_locked"):
        held.add("*")  # name convention: caller holds whichever lock applies
    first_body_line = fn.body[0].lineno if fn.body else fn.lineno
    for line in range(fn.lineno, first_body_line + 1):
        marker = src.lock_held_marker(line)
        if marker:
            held.add(marker)
    return held


def check_locks(tree: ast.Module, src: SourceFile) -> list[Finding]:
    findings: list[Finding] = []
    classes = collect_guarded(tree, src)
    if not classes:
        return findings

    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef) or cls.name not in classes:
            continue
        gc = classes[cls.name]
        for fn in cls.body:
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if fn.name in ("__init__", "__post_init__"):
                continue
            _scan(fn, gc, src, findings, fn.name)
    return findings


def _scan(method_fn: ast.FunctionDef, gc: GuardedClass, src: SourceFile,
          findings: list[Finding], method: str) -> None:
    """Walk one method body tracking the lexically-open lock set."""

    def check(node: ast.AST, held: set[str]) -> None:
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"
                and node.attr in gc.guarded):
            lock = gc.guarded[node.attr]
            if lock not in held and "*" not in held:
                f = src.finding(
                    RULE_LOCK, node.lineno,
                    f"{gc.name}.{method}: `self.{node.attr}` accessed "
                    f"without holding `self.{lock}` "
                    f"(guarded-by: {lock})",
                )
                if f is not None:
                    findings.append(f)

    def visit(node: ast.AST, held: set[str]) -> None:
        if isinstance(node, (ast.With, ast.AsyncWith)):
            # with-items themselves evaluate under the OUTER lockset
            for item in node.items:
                visit(item, held)
            inner = held | _with_locks(node)
            for stmt in node.body:
                visit(stmt, inner)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # closures run later, on whatever thread calls them: they only
            # hold what their own markers declare
            nested = _method_held_locks(node, src)
            for child in ast.iter_child_nodes(node):
                visit(child, nested)
            return
        if isinstance(node, ast.Lambda):
            visit(node.body, set())
            return
        check(node, held)
        for child in ast.iter_child_nodes(node):
            visit(child, held)

    visit(method_fn, set())
