"""Runtime sanitizer: opt-in dynamic checks for the engine's contracts.

Enabled by ``SENTIO_SANITIZE=1`` (read at object construction). Three
checks, all free when disabled:

* **lock ownership** — ``make_lock`` returns an :class:`OwnedLock` that
  records its owning thread; helpers documented as lock-held call
  :func:`assert_held` at entry, so "caller must hold the lock" stops being
  a comment. Disabled, ``make_lock`` returns a plain ``threading.Lock`` and
  ``assert_held`` no-ops.
* **single-driver-thread engine** — the paged engine is touched only by
  one driver (the serving pump, or the test/bench thread driving it
  directly). :class:`ThreadGuard` binds the first mutating caller and
  raises on any mutating entry from a different live thread; the serving
  pump rebinds explicitly at pump start (:func:`bind_engine_owner`) since
  pump threads are born and die per burst.
* **engine invariants** — after every tick,
  :func:`check_engine_invariants` verifies page-pool conservation (every
  page id 1..P-1 is owned by exactly one of: the free list, an active
  slot, the radix cache) and radix refcount consistency (each node's
  refcount equals the number of active slots whose pinned chain crosses
  it). A leaked or double-owned page fails THE TICK THAT LEAKED IT, not a
  pool-exhaustion three workloads later.
* **lock order** — every :class:`OwnedLock` acquisition is checked against
  a global acquired-while-holding edge set; the first blocking acquire
  that reverses an already-observed edge raises *before* taking the lock,
  so the inversion is reported on the run that merely COULD have
  deadlocked, not the run that did. Reentrant blocking acquire of the
  same (non-reentrant) lock raises for the same reason.
* **locksets** — :func:`guard_locksets` is a class decorator that reads
  the class's own ``# guarded-by:`` annotations (via the static
  checker's parser) and enforces them dynamically, Eraser-style: each
  annotated attribute carries a candidate lockset, intersected with the
  thread's held locks at every write once a second thread has touched
  it; an empty intersection raises. This is the dynamic complement of
  the lexical ``lock-discipline`` rule — it sees through ``_locked``
  suffixes and ``# lock-held:`` markers, because it checks what the
  thread actually holds.
"""

from __future__ import annotations

import functools
import os
import threading
from typing import Optional

__all__ = [
    "SanitizerError",
    "enabled",
    "make_lock",
    "assert_held",
    "OwnedLock",
    "ThreadGuard",
    "engine_guard",
    "bind_engine_owner",
    "check_engine_invariants",
    "guard_locksets",
    "held_lock_names",
]


class SanitizerError(RuntimeError):
    """An engine/lock contract was violated (only raised under
    ``SENTIO_SANITIZE=1``)."""


def enabled() -> bool:
    return os.environ.get("SENTIO_SANITIZE", "") == "1"


# ----------------------------------------------------- runtime lock order

# Per-thread stack of (lock name, lock id) currently held, maintained by
# OwnedLock. The stack is what makes both dynamic checks possible: the
# order checker reads it to learn what is held while acquiring, the
# lockset checker reads it to learn what is held while writing.
_held = threading.local()

# Acquired-while-holding edges observed so far, process-global and keyed by
# lock NAME (make_lock names are class-qualified, so two instances of one
# class share an edge — same aliasing the static lock graph uses). Value is
# a human-readable note of who established the edge, for the error message.
_order_edges: dict = {}
_order_guard = threading.Lock()  # plain lock: must not feed its own stack


def _held_stack() -> list:
    stack = getattr(_held, "stack", None)
    if stack is None:
        stack = _held.stack = []
    return stack


def held_lock_names() -> frozenset:
    """Names of every :class:`OwnedLock` the calling thread holds."""
    return frozenset(name for name, _ in _held_stack())


def _reset_lock_order() -> None:
    """Test hook: forget every observed acquisition edge."""
    with _order_guard:
        _order_edges.clear()


def _note_acquire(name: str, obj: object) -> None:
    """Pre-acquire check for a blocking acquire: raises on reentrancy or on
    the first observed order inversion. Runs BEFORE the underlying acquire,
    so a raise leaves nothing newly held."""
    stack = _held_stack()
    cur = threading.current_thread()
    for held_name, held_id in stack:
        if held_id == id(obj):
            raise SanitizerError(
                f"self-deadlock: thread {cur.name!r} blocking on "
                f"{name!r} while already holding it (non-reentrant lock)"
            )
    if not stack:
        return
    with _order_guard:
        for held_name, _hid in stack:
            if held_name == name:
                continue  # distinct instances of one class: no order info
            if (name, held_name) in _order_edges:
                raise SanitizerError(
                    f"lock-order inversion: thread {cur.name!r} acquiring "
                    f"{name!r} while holding {held_name!r}, but the reverse "
                    f"order was already observed "
                    f"({_order_edges[(name, held_name)]}) — two threads "
                    f"entering from opposite edges deadlock; pick one "
                    f"global order"
                )
            _order_edges.setdefault(
                (held_name, name),
                f"{held_name} -> {name} by thread {cur.name!r}",
            )


def _push_held(name: str, obj: object) -> None:
    _held_stack().append((name, id(obj)))


def _pop_held(obj: object) -> None:
    stack = _held_stack()
    for i in range(len(stack) - 1, -1, -1):
        if stack[i][1] == id(obj):
            del stack[i]
            return


# ------------------------------------------------------------ lock ownership


class OwnedLock:
    """``threading.Lock`` recording its owning thread, so lock-held helpers
    can assert the caller actually holds it. Not reentrant (neither is the
    lock it wraps). Every acquisition feeds the per-thread held stack and
    the global order-edge set (see the lock-order section above)."""

    def __init__(self, name: str = "lock") -> None:
        self.name = name
        self._lock = threading.Lock()
        self._owner: Optional[threading.Thread] = None

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if blocking:
            _note_acquire(self.name, self)
        got = self._lock.acquire(blocking, timeout)
        if got:
            self._owner = threading.current_thread()
            _push_held(self.name, self)
        return got

    def release(self) -> None:
        self._owner = None
        _pop_held(self)
        self._lock.release()

    def __enter__(self) -> "OwnedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._lock.locked()

    @property
    def held_by_me(self) -> bool:
        return self._owner is threading.current_thread()


def make_lock(name: str = "lock"):
    """A lock for a ``guarded-by`` annotated structure: plain
    ``threading.Lock`` normally, :class:`OwnedLock` under the sanitizer."""
    return OwnedLock(name) if enabled() else threading.Lock()


def assert_held(lock) -> None:
    """No-op on a plain lock; on an :class:`OwnedLock`, raise unless the
    calling thread holds it."""
    if isinstance(lock, OwnedLock) and not lock.held_by_me:
        raise SanitizerError(
            f"lock-held contract violated: {lock.name} is not held by "
            f"thread {threading.current_thread().name!r}"
        )


# --------------------------------------------------- single-driver contract


class ThreadGuard:
    """Binds the engine's driver thread and rejects mutating entry from any
    other live thread. First mutating caller binds implicitly (tests/bench
    drive the engine directly); the serving pump rebinds explicitly at pump
    start — an authorized ownership transfer, since the service guarantees
    at most one pump exists."""

    def __init__(self, name: str = "engine") -> None:
        self.name = name
        self._owner: Optional[threading.Thread] = None

    def bind(self) -> None:
        self._owner = threading.current_thread()

    def enter(self, op: str) -> None:
        cur = threading.current_thread()
        owner = self._owner
        if owner is None or owner is cur:
            # baselined cross-thread-race: the guard's own owner field is
            # deliberately lock-free — it exists to DETECT cross-thread
            # entry, and a mutex here would serialize every engine call the
            # sanitizer observes; a torn owner read merely reports the race
            # it was about to report anyway
            self._owner = cur
            return
        if not owner.is_alive():
            # the previous driver died (a finished pump burst): ownership
            # migrates to whoever drives next
            self._owner = cur
            return
        raise SanitizerError(
            f"{self.name}.{op} called from thread {cur.name!r} while the "
            f"engine is owned by live thread {owner.name!r} — the engine is "
            f"single-threaded by contract (runtime/service.py); route calls "
            f"through the pump"
        )


def engine_guard(name: str = "engine") -> Optional[ThreadGuard]:
    """A :class:`ThreadGuard` when sanitizing, else None (so the per-call
    cost in the engine is one attribute test)."""
    return ThreadGuard(name) if enabled() else None


def bind_engine_owner(engine) -> None:
    """Explicitly hand engine ownership to the calling thread (the serving
    pump calls this at pump start). No-op when the engine carries no guard."""
    guard = getattr(engine, "_san", None)
    if guard is not None:
        guard.bind()


# --------------------------------------------------------- lockset checker


class _LocksetState:
    """Per-instance lockset tracking for one guard_locksets instance.

    ``spec`` maps attr -> declared lock attr name (from the class's own
    ``# guarded-by:`` annotations). ``records`` maps attr ->
    ``[last_writer_thread, candidate_lockset_or_None]``; ``None`` marks the
    exclusive phase (only one thread has ever written the attr — Eraser's
    initialization grace period, which also absorbs single-threaded use)."""

    __slots__ = ("spec", "records")

    def __init__(self, spec: dict) -> None:
        self.spec = spec
        self.records: dict = {}


# class -> attr->lock spec parsed from its source (lazily; None = no spec)
_lockset_specs: dict = {}


def _lockset_spec(cls) -> dict:
    spec = _lockset_specs.get(cls)
    if spec is None:
        import ast
        import inspect
        import textwrap
        from pathlib import Path

        from sentio_tpu.analysis.findings import SourceFile
        from sentio_tpu.analysis.locks import collect_guarded

        try:
            text = textwrap.dedent(inspect.getsource(cls))
            tree = ast.parse(text)
        except (OSError, TypeError, SyntaxError):
            spec = {}
        else:
            src = SourceFile(path=Path("<runtime>"), rel="<runtime>", text=text)
            gc = collect_guarded(tree, src).get(cls.__name__)
            # mutex-guarded attrs only: THREAD_LOCKS ownership is enforced
            # by ThreadGuard, not locksets
            spec = dict(gc.guarded) if gc else {}
        _lockset_specs[cls] = spec
    return spec


def _lockset_write(obj, state: _LocksetState, attr: str) -> None:
    cur = threading.current_thread()
    rec = state.records.get(attr)
    if rec is None:
        state.records[attr] = [cur, None]
        return
    if rec[1] is None and rec[0] is cur:
        return  # still exclusive
    held = held_lock_names()
    cand = held if rec[1] is None else rec[1] & held
    rec[0] = cur
    rec[1] = cand
    if not cand:
        raise SanitizerError(
            f"lockset violation: {type(obj).__name__}.{attr} "
            f"(guarded-by: {state.spec[attr]}) written by thread "
            f"{cur.name!r} and its candidate lockset is now empty — "
            f"no single lock protects every write; this write holds "
            f"{sorted(held) or 'nothing'}"
        )


def _install_lockset_setattr(cls) -> None:
    if "_san_setattr_installed" in cls.__dict__:
        return
    orig = cls.__setattr__

    def __setattr__(self, name, value):
        state = self.__dict__.get("_san_lockset_state")
        if state is not None and name in state.spec:
            _lockset_write(self, state, name)
        orig(self, name, value)

    cls.__setattr__ = __setattr__
    cls._san_setattr_installed = True


def _arm_locksets(obj, cls) -> None:
    spec = _lockset_spec(cls)
    if not spec:
        return
    # only attrs whose declared lock is an OwnedLock on this instance are
    # observable (a plain Lock never feeds the held stack, so checking
    # against it would be all false positives)
    usable = {
        attr: lock for attr, lock in spec.items()
        if isinstance(getattr(obj, lock, None), OwnedLock)
    }
    if not usable:
        return
    _install_lockset_setattr(cls)
    obj.__dict__["_san_lockset_state"] = _LocksetState(usable)


def guard_locksets(cls):
    """Class decorator: enforce the class's own ``# guarded-by:``
    annotations dynamically, Eraser-style (Savage et al., TOSP 1997).

    Free when ``SENTIO_SANITIZE`` is unset: the env is read at instance
    construction, and an unarmed instance pays nothing — ``__setattr__``
    is only replaced on the class once some instance arms, and even then
    the fast path is one dict probe.

    Armed, every rebind of an annotated attribute runs the lockset state
    machine: the first writing thread owns the attr exclusively; the
    moment a second thread writes, the candidate lockset becomes the
    locks that thread holds, and every later write (from any thread)
    intersects it with the writer's held set. Empty intersection raises
    :class:`SanitizerError` — there is provably no single lock protecting
    the attribute, whatever the annotation claims. Writes during
    ``__init__`` predate arming and are exempt, mirroring the static
    rule. Granularity is attribute REBIND (``self.x = ...``,
    ``self.x += ...``); in-place mutation of a guarded container is the
    static rule's job."""
    orig_init = cls.__init__

    @functools.wraps(orig_init)
    def __init__(self, *args, **kwargs):
        orig_init(self, *args, **kwargs)
        if enabled():
            _arm_locksets(self, cls)

    cls.__init__ = __init__
    return cls


# ------------------------------------------------------- engine invariants


def _radix_nodes(radix):
    stack = list(radix.root.children.values())
    while stack:
        node = stack.pop()
        stack.extend(node.children.values())
        yield node


def _check_pool_repr(engine) -> None:
    """KV-pool representation consistency: the quantized pool is a
    ``{"q": int8, "s": f16}`` pytree whose scale tree mirrors the payload
    shape minus the vector axis; the unquantized pool is a plain array.
    Pure host-side metadata checks (shape/dtype/type), no device sync —
    a repr drift (e.g. a refactor materializing a dense copy into the
    pool slot, or dropping the scale tree) fails the tick that did it."""
    pool = getattr(engine, "pool", None)
    if pool is None:
        return
    want_quant = getattr(engine, "kv_quant", "none") == "int8"
    if bool(getattr(pool, "quantized", False)) != want_quant:
        raise SanitizerError(
            f"pool.quantized={getattr(pool, 'quantized', None)} but engine "
            f"kv_quant={getattr(engine, 'kv_quant', None)!r}"
        )
    for name, side in (("k", pool.k), ("v", pool.v)):
        if not want_quant:
            if isinstance(side, dict):
                raise SanitizerError(
                    f"pool.{name} is a dict pytree on an unquantized engine"
                )
            continue
        if not isinstance(side, dict) or set(side) != {"q", "s"}:
            raise SanitizerError(
                f"quantized pool.{name} must be a {{'q','s'}} pytree, got "
                f"{sorted(side) if isinstance(side, dict) else type(side).__name__}"
            )
        q, s = side["q"], side["s"]
        if str(q.dtype) != "int8" or str(s.dtype) != "float16":
            raise SanitizerError(
                f"quantized pool.{name} dtypes drifted: q={q.dtype} "
                f"(want int8), s={s.dtype} (want float16)"
            )
        if tuple(q.shape[:-1]) != tuple(s.shape):
            raise SanitizerError(
                f"quantized pool.{name} scale shape {tuple(s.shape)} does "
                f"not mirror payload {tuple(q.shape)} minus the vector axis"
            )


def check_engine_invariants(engine) -> None:
    """Page-pool conservation + radix refcount consistency. Called by the
    engine at the end of every tick under the sanitizer.

    Ownership model being verified: page 0 is scratch; every other page id
    is owned by exactly one of (a) the allocator free list, (b) an active
    slot's ``pages`` minus the span it donated to the radix cache, (c) the
    radix tree. Refcounts: each active slot pins the chain from its
    ``prefix_node`` to the root, contributing exactly 1 per node. The pool
    representation check (:func:`_check_pool_repr`) runs first so the
    quantized ``{"q","s"}`` pool is held to the same per-tick standard as
    plain arrays."""
    _check_pool_repr(engine)
    alloc = engine.allocator
    free = list(alloc._free)
    free_set = set(free)
    if len(free_set) != len(free):
        raise SanitizerError(
            f"page free-list contains duplicates: "
            f"{sorted(p for p in free_set if free.count(p) > 1)}"
        )
    if 0 in free_set or any(p < 0 or p >= alloc.num_pages for p in free_set):
        raise SanitizerError("free-list holds out-of-range or scratch page ids")

    slot_pages: list[int] = []
    donated: set[int] = set()
    for slot in engine.slots:
        if not slot.active:
            continue
        slot_pages.extend(slot.pages)
        donated.update(slot.donated)
    if len(set(slot_pages)) != len(slot_pages):
        raise SanitizerError("a page id is owned by two active slots")
    slot_owned = set(slot_pages) - donated

    radix = getattr(engine, "_radix", None)
    radix_pages: set[int] = set()
    if radix is not None:
        for node in _radix_nodes(radix):
            for p in node.pages:
                if p in radix_pages:
                    raise SanitizerError(
                        f"radix tree holds page {p} in two nodes"
                    )
                radix_pages.add(p)
        if len(radix_pages) != radix.pages_held:
            raise SanitizerError(
                f"radix pages_held={radix.pages_held} but tree holds "
                f"{len(radix_pages)} pages"
            )

    for a, b, what in (
        (free_set, slot_owned, "free list and an active slot"),
        (free_set, radix_pages, "free list and the radix cache"),
        (slot_owned, radix_pages, "an active slot and the radix cache"),
    ):
        both = a & b
        if both:
            raise SanitizerError(
                f"pages {sorted(both)} owned by {what} simultaneously"
            )

    expected = set(range(1, alloc.num_pages))
    union = free_set | slot_owned | radix_pages
    if union != expected:
        leaked = sorted(expected - union)
        extra = sorted(union - expected)
        raise SanitizerError(
            f"page conservation violated: leaked={leaked} unknown={extra} "
            f"(free={len(free_set)} slot={len(slot_owned)} "
            f"radix={len(radix_pages)} total={alloc.num_pages - 1})"
        )

    if radix is not None:
        expected_rc: dict[int, int] = {}
        for slot in engine.slots:
            if not slot.active:
                continue
            node = slot.prefix_node
            while node is not None and node is not radix.root:
                expected_rc[id(node)] = expected_rc.get(id(node), 0) + 1
                node = node.parent
        for node in _radix_nodes(radix):
            want = expected_rc.get(id(node), 0)
            if node.refcount != want:
                raise SanitizerError(
                    f"radix refcount mismatch on node "
                    f"({len(node.tokens)} tokens, pages {node.pages}): "
                    f"refcount={node.refcount} but {want} live slot chains "
                    f"cross it"
                )
