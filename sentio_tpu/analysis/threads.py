"""Thread-role model + cross-thread race rule (whole-program).

The serving tier is deeply multithreaded (pump, supervisor, socket
dispatcher, registry accepter, telemetry/status loops, detached verify,
warmup/drain workers ...) but :mod:`.locks` checks lock discipline
*lexically* and *file-locally*: it can say "this access holds the lock"
but not "which threads can get here at all". This module upgrades the
model from lexical to call-graph-aware:

* **thread-role registry** (``thread-role`` rule) — every
  ``threading.Thread(...)`` construction must carry a ``name=`` that
  resolves to a role in the bounded :data:`ROLE_REGISTRY` (pattern match
  on the statically-resolvable part of the name, or an explicit
  ``# thread-role: <role>`` comment on the construction for dynamic
  names). An unnamed or unregistered spawn is a finding: anonymous
  threads are invisible to every downstream concurrency rule.

* **intra-package call graph** — every ``def`` in the linted program is
  a node; edges come from ``self.method()`` calls (with single-level
  base-class resolution), bare-name calls through the lexical scope
  chain (closures included — warmup/drain workers are closures), calls
  through ``from pkg.mod import fn`` / ``import pkg.mod as alias``
  imports, and ``obj.method()`` calls whose method name is defined by
  exactly one class in the program (and is not a generic verb). Passing
  a function as a *value* (``target=self._run``) is NOT a call edge —
  that reference is what creates a role, below.

* **role reachability** — from each spawn's ``target`` the call graph
  yields the set of functions that role can execute. Everything
  reachable from the public surface (non-underscore functions/methods
  and dunders) additionally carries the pseudo-role ``caller``: the
  main thread, API handlers, and test drivers all enter there.

* **cross-thread race rule** (``cross-thread-race``) — a ``self.<attr>``
  mutated (assigned, aug-assigned, subscript-stored, or hit with a
  mutating container method) outside ``__init__`` from functions whose
  role sets union to ≥ 2 roles, with no ``guarded-by`` annotation, is a
  finding: two threads can write it and no lock is even *declared*. An
  attribute annotated with a :data:`~.locks.THREAD_LOCKS` owner
  (``engine-thread`` / ``pump-thread``) that is *accessed at all* from a
  role outside the owner set is likewise a finding — thread-ownership
  is only sound if foreign roles provably cannot reach the attribute.

The model is deliberately an under-approximation (unresolvable dynamic
calls produce no edges), so every finding corresponds to a concrete
spawn-to-access path; missing edges cost recall, never precision.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Optional

from sentio_tpu.analysis.findings import Finding, SourceFile
from sentio_tpu.analysis.locks import GuardedClass, collect_guarded

__all__ = [
    "ROLE_REGISTRY",
    "CALLER_ROLE",
    "Program",
    "build_program",
    "check_thread_model",
]

RULE_ROLE = "thread-role"
RULE_RACE = "cross-thread-race"

#: Pseudo-role carried by everything reachable from the public surface:
#: the main thread, HTTP handlers, and test/bench drivers.
CALLER_ROLE = "caller"

#: The bounded role registry: role -> thread-name patterns (``*`` matches
#: any run of characters). A spawn whose ``name=`` matches no pattern and
#: carries no ``# thread-role:`` annotation is a ``thread-role`` finding.
ROLE_REGISTRY: dict[str, tuple[str, ...]] = {
    "pump": ("paged-decode-pump",),
    "supervisor": ("replica-supervisor",),
    "dispatcher": ("replica-worker-rx-*",),
    "rpc": ("worker-rpc-*",),
    "accepter": ("worker-registry-accept", "worker-registry-handshake",
                 "worker-serve-conn"),
    "autoscaler": ("fleet-autoscaler",),
    "telemetry": ("worker-telemetry",),
    "status": ("worker-status",),
    "detached-verify": ("graph-detached-*",),
    "warmup": ("replica-warmup-*", "paged-warmup-*"),
    "drain": ("replica-drain-*",),
    "batcher": ("thread-batcher", "*-batcher"),
    "health-probe": ("qdrant-health-*", "replica-worker-ping-*"),
    "rebuild": ("replica-rebuild-*",),
    "eval-worker": ("eval-worker-*",),
    "cache-fill": ("embedder-cache-fill",),
    "mock-api": ("mock-model-api",),
}

#: Thread-ownership annotations (locks.THREAD_LOCKS) -> roles allowed to
#: touch the attribute. ``caller`` is always allowed: tests and bench
#: drive the engine from the main thread, and the runtime sanitizer's
#: ThreadGuard enforces the single-driver handoff dynamically.
THREAD_OWNER_ROLES: dict[str, frozenset[str]] = {
    "engine-thread": frozenset({"pump", CALLER_ROLE}),
    "pump-thread": frozenset({"pump", CALLER_ROLE}),
}

_THREAD_ROLE_RE = re.compile(r"#\s*thread-role:\s*([\w-]+)")

# obj.method() calls resolve through the program-wide method index only
# when the name is unambiguous AND not one of these generic verbs — a
# `.close()` matching some unrelated class would wire fantasy edges.
_GENERIC_METHODS = frozenset({
    "get", "put", "set", "add", "pop", "close", "open", "start", "stop",
    "run", "join", "wait", "send", "recv", "read", "write", "append",
    "clear", "update", "items", "keys", "values", "acquire", "release",
    "submit", "step", "generate", "encode", "decode", "flush", "reset",
    "copy", "next", "result", "cancel", "done", "info", "warning",
    "error", "debug", "exception", "search", "match", "group", "strip",
    "split", "lower", "upper", "format", "remove", "insert", "extend",
    "count", "index", "sort", "setdefault", "discard", "notify",
    "notify_all", "is_alive", "is_set", "empty", "name",
    "cleanup", "setup", "shutdown", "terminate", "kill", "connect",
    "disconnect", "listen", "accept", "handle", "apply", "fetch", "load",
    "save", "dump", "emit", "poll", "push", "pull", "peek", "ping",
    "stat", "stats", "item", "mean", "sum", "max", "min", "all", "any",
    "tolist", "astype", "serve_forever", "invoke", "render", "build",
})

# container-mutating method names: `self.attr.append(x)` counts as a
# mutation of `attr` for the race rule
_MUTATORS = frozenset({
    "append", "extend", "insert", "pop", "popleft", "appendleft",
    "remove", "clear", "add", "discard", "update", "setdefault",
    "__setitem__", "__delitem__", "sort", "reverse", "rotate",
})


# --------------------------------------------------------------- model types


FuncKey = tuple[str, str]  # (repo-relative path, dotted qualname)


@dataclass
class FuncInfo:
    key: FuncKey
    name: str
    module: str                       # repo-relative path
    class_name: Optional[str]         # innermost enclosing class
    node: ast.AST
    src: SourceFile
    visible: dict[str, FuncKey]       # lexically visible callables
    # self.<attr> accesses in the IMMEDIATE body (nested defs excluded —
    # they are their own FuncInfo, sharing class_name through the closure)
    writes: dict[str, list[int]] = field(default_factory=dict)
    reads: dict[str, list[int]] = field(default_factory=dict)
    calls: list[ast.Call] = field(default_factory=list)
    withs: list[ast.With] = field(default_factory=list)


@dataclass
class ThreadSpawn:
    src: SourceFile
    lineno: int
    in_class: Optional[str]
    name_pattern: Optional[str]   # resolved name ('*' for dynamic parts)
    role: Optional[str]
    annotation: Optional[str]     # explicit # thread-role: value
    target_key: Optional[FuncKey]
    unnamed: bool = False


@dataclass
class Program:
    """Whole-program view shared by the thread-role and lock-order rules."""

    files: list[tuple[ast.Module, SourceFile]]
    functions: dict[FuncKey, FuncInfo] = field(default_factory=dict)
    edges: dict[FuncKey, set[FuncKey]] = field(default_factory=dict)
    spawns: list[ThreadSpawn] = field(default_factory=list)
    # (module rel, class name) -> guarded annotations for that class
    guarded: dict[tuple[str, str], GuardedClass] = field(default_factory=dict)
    # class name -> [(module rel, ClassDef)] across the program
    classes: dict[str, list[tuple[str, ast.ClassDef]]] = field(default_factory=dict)
    # function role sets (filled by _assign_roles)
    func_roles: dict[FuncKey, set[str]] = field(default_factory=dict)
    # module-level lock names per module (for lockorder): name -> lock id
    module_locks: dict[str, dict[str, str]] = field(default_factory=dict)
    # direct-method name -> keys of every class method with that name
    method_index: dict[str, list[FuncKey]] = field(default_factory=dict)

    def roles_of(self, key: FuncKey) -> set[str]:
        return self.func_roles.get(key, set())


# ------------------------------------------------------------ name matching


def _pattern_to_regex(pattern: str) -> re.Pattern:
    return re.compile(
        "".join(".*" if ch == "*" else re.escape(ch) for ch in pattern) + r"\Z"
    )


_ROLE_PATTERNS = [
    (role, _pattern_to_regex(p))
    for role, pats in ROLE_REGISTRY.items()
    for p in pats
]


def resolve_role(name_pattern: str) -> Optional[str]:
    """Match a (possibly wildcarded) thread name against the registry.
    ``*`` in the candidate stands for a runtime-formatted segment; it is
    encoded as a char the registry's own wildcards match."""
    probe = name_pattern.replace("*", "\x00")  # '.*' matches the marker
    for role, rx in _ROLE_PATTERNS:
        if rx.match(probe):
            return role
    return None


def _static_name(expr: ast.expr) -> Optional[str]:
    """Resolve a thread ``name=`` expression to a wildcard pattern, or
    None when nothing about it is static."""
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return expr.value
    if isinstance(expr, ast.JoinedStr):
        parts = []
        for v in expr.values:
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                parts.append(v.value)
            else:
                parts.append("*")
        pat = "".join(parts)
        return pat if pat.strip("*") else None
    return None


def _thread_role_annotation(src: SourceFile, node: ast.AST) -> Optional[str]:
    for line in range(node.lineno, getattr(node, "end_lineno", node.lineno) + 1):
        m = _THREAD_ROLE_RE.search(src.line_text(line))
        if m:
            return m.group(1)
    return None


# ------------------------------------------------------------ program build


def _module_dotted(rel: str) -> Optional[str]:
    if not rel.endswith(".py"):
        return None
    parts = rel[:-3].split("/")
    if parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


class _ModuleIndex:
    """Per-module symbol tables used during edge resolution."""

    def __init__(self) -> None:
        self.funcs: dict[str, FuncKey] = {}          # module-level defs
        self.import_funcs: dict[str, tuple[str, str]] = {}  # name -> (dotted mod, attr)
        self.import_mods: dict[str, str] = {}        # alias -> dotted module
        self.locks: dict[str, str] = {}              # module-level lock names


def _is_lock_factory(call: ast.expr) -> bool:
    if not isinstance(call, ast.Call):
        return False
    fn = call.func
    name = fn.attr if isinstance(fn, ast.Attribute) else (
        fn.id if isinstance(fn, ast.Name) else "")
    return name in ("Lock", "RLock", "Condition", "make_lock")


def build_program(files: list[tuple[ast.Module, SourceFile]]) -> Program:
    prog = Program(files=files)
    mod_index: dict[str, _ModuleIndex] = {}
    dotted_to_rel: dict[str, str] = {}
    for _tree, src in files:
        dotted = _module_dotted(src.rel)
        if dotted:
            dotted_to_rel[dotted] = src.rel

    # ---- pass 1: symbols, functions, classes, guarded annotations
    for tree, src in files:
        idx = _ModuleIndex()
        mod_index[src.rel] = idx
        for cls_name, gc in collect_guarded(tree, src).items():
            prog.guarded[(src.rel, cls_name)] = gc
        for stmt in tree.body:
            if isinstance(stmt, ast.Import):
                for alias in stmt.names:
                    idx.import_mods[alias.asname or alias.name.split(".")[0]] = (
                        alias.name
                    )
            elif isinstance(stmt, ast.ImportFrom) and stmt.module:
                for alias in stmt.names:
                    idx.import_funcs[alias.asname or alias.name] = (
                        stmt.module, alias.name
                    )
            elif isinstance(stmt, ast.Assign) and _is_lock_factory(stmt.value):
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        stem = src.rel.rsplit("/", 1)[-1][:-3]
                        idx.locks[t.id] = f"{stem}.{t.id}"
        prog.module_locks[src.rel] = idx.locks

        def register(node: ast.AST, qual: list[str], cls: Optional[str],
                     visible: dict[str, FuncKey]) -> None:
            for child in (node.body if hasattr(node, "body") else []):
                if isinstance(child, ast.ClassDef):
                    prog.classes.setdefault(child.name, []).append(
                        (src.rel, child))
                    register(child, qual + [child.name], child.name, dict(visible))
                elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    key = (src.rel, ".".join(qual + [child.name]))
                    # nested defs are visible to siblings defined later AND
                    # earlier (runtime order rarely matters for our reach)
                    visible[child.name] = key
                    if not qual:
                        idx.funcs[child.name] = key
                    info = FuncInfo(
                        key=key, name=child.name, module=src.rel,
                        class_name=cls, node=child, src=src,
                        visible=dict(visible),
                    )
                    prog.functions[key] = info
                    register(child, qual + [child.name], cls, info.visible)
                elif isinstance(child, (ast.If, ast.Try)):
                    register(child, qual, cls, visible)

        register(tree, [], None, {})

    # two-phase sibling visibility: a def earlier in a scope must see defs
    # later in the same scope (mutual recursion) — rebuild visible maps by
    # merging every sibling registered under the same parent scope
    by_scope: dict[tuple[str, str], dict[str, FuncKey]] = {}
    for key, info in prog.functions.items():
        scope = (info.module, key[1].rsplit(".", 1)[0] if "." in key[1] else "")
        by_scope.setdefault(scope, {})[info.name] = key
    for key, info in prog.functions.items():
        scope = (info.module, key[1].rsplit(".", 1)[0] if "." in key[1] else "")
        info.visible.update(by_scope.get(scope, {}))

    for key, f in prog.functions.items():
        if f.class_name and key[1] == f"{f.class_name}.{f.name}":
            prog.method_index.setdefault(f.name, []).append(key)

    # ---- pass 2: per-function bodies — accesses, calls, withs, spawns
    for tree, src in files:
        for key, info in prog.functions.items():
            if info.module != src.rel:
                continue
            _scan_body(prog, info)

    # ---- pass 3: call edges + spawn targets
    for key, info in prog.functions.items():
        out = prog.edges.setdefault(key, set())
        for call in info.calls:
            callee = _resolve_call(prog, mod_index, dotted_to_rel, info,
                                   call.func)
            if callee is not None:
                out.add(callee)
            spawn = _extract_spawn(prog, mod_index, dotted_to_rel, info, call)
            if spawn is not None:
                prog.spawns.append(spawn)

    _assign_roles(prog)
    return prog


def _scan_body(prog: Program, info: FuncInfo) -> None:
    """Collect self-attribute accesses / calls / withs from the immediate
    body of one function (nested defs excluded)."""

    def visit(node: ast.AST) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return  # separate FuncInfo / opaque
        if isinstance(node, ast.Call):
            info.calls.append(node)
            fn = node.func
            # self.attr.append(...) — a container mutation of attr
            if (isinstance(fn, ast.Attribute) and fn.attr in _MUTATORS
                    and isinstance(fn.value, ast.Attribute)
                    and isinstance(fn.value.value, ast.Name)
                    and fn.value.value.id == "self"):
                info.writes.setdefault(fn.value.attr, []).append(fn.lineno)
        if isinstance(node, (ast.With, ast.AsyncWith)):
            info.withs.append(node)
        if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
                and node.value.id == "self":
            if isinstance(node.ctx, (ast.Store, ast.Del)):
                info.writes.setdefault(node.attr, []).append(node.lineno)
            else:
                info.reads.setdefault(node.attr, []).append(node.lineno)
        # self.attr[k] = v mutates attr even though attr is a Load
        if isinstance(node, ast.Subscript) and isinstance(node.ctx, (ast.Store, ast.Del)):
            tgt = node.value
            if (isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"):
                info.writes.setdefault(tgt.attr, []).append(node.lineno)
        for child in ast.iter_child_nodes(node):
            visit(child)

    node = info.node
    for child in ast.iter_child_nodes(node):
        visit(child)


def _method_on_class(prog: Program, module: str, cls_name: str,
                     meth: str, depth: int = 0) -> Optional[FuncKey]:
    """Resolve a method on a class, walking base classes by name (single
    inheritance chains, bounded depth)."""
    if depth > 4:
        return None
    candidates = prog.classes.get(cls_name, [])
    # prefer the class defined in the calling module (shadowed names)
    candidates = sorted(candidates, key=lambda rn: rn[0] != module)
    for rel, node in candidates:
        key = (rel, f"{node.name}.{meth}")
        if key in prog.functions:
            return key
        for base in node.bases:
            base_name = base.id if isinstance(base, ast.Name) else (
                base.attr if isinstance(base, ast.Attribute) else None)
            if base_name and base_name in prog.classes:
                found = _method_on_class(prog, rel, base_name, meth, depth + 1)
                if found:
                    return found
    return None


def _resolve_call(prog: Program, mod_index: dict[str, _ModuleIndex],
                  dotted_to_rel: dict[str, str], info: FuncInfo,
                  fn: ast.expr) -> Optional[FuncKey]:
    idx = mod_index[info.module]
    if isinstance(fn, ast.Name):
        # lexical chain: closures/siblings, then module defs, then imports
        if fn.id in info.visible:
            return info.visible[fn.id]
        if fn.id in idx.funcs:
            return idx.funcs[fn.id]
        if fn.id in idx.import_funcs:
            dotted, attr = idx.import_funcs[fn.id]
            rel = dotted_to_rel.get(dotted)
            if rel:
                key = (rel, attr)
                if key in prog.functions:
                    return key
        return None
    if isinstance(fn, ast.Attribute):
        base = fn.value
        if isinstance(base, ast.Name):
            if base.id in ("self", "cls") and info.class_name:
                return _method_on_class(prog, info.module, info.class_name,
                                        fn.attr)
            if base.id in idx.import_mods:
                rel = dotted_to_rel.get(idx.import_mods[base.id])
                if rel:
                    key = (rel, fn.attr)
                    if key in prog.functions:
                        return key
                return None
            if base.id in prog.classes:
                return _method_on_class(prog, info.module, base.id, fn.attr)
        # obj.method(): unique-name resolution, generic verbs excluded
        if fn.attr in _GENERIC_METHODS or fn.attr.startswith("__"):
            return None
        owners = prog.method_index.get(fn.attr, [])
        if len(owners) == 1:
            return owners[0]
        return None
    return None


def _is_thread_ctor(fn: ast.expr) -> bool:
    if isinstance(fn, ast.Attribute) and fn.attr == "Thread":
        return isinstance(fn.value, ast.Name) and fn.value.id == "threading"
    return isinstance(fn, ast.Name) and fn.id == "Thread"


def _extract_spawn(prog: Program, mod_index: dict[str, _ModuleIndex],
                   dotted_to_rel: dict[str, str], info: FuncInfo,
                   call: ast.Call) -> Optional[ThreadSpawn]:
    if not _is_thread_ctor(call.func):
        return None
    name_expr = None
    target_expr = None
    for kw in call.keywords:
        if kw.arg == "name":
            name_expr = kw.value
        elif kw.arg == "target":
            target_expr = kw.value
    annotation = _thread_role_annotation(info.src, call)
    name_pattern = _static_name(name_expr) if name_expr is not None else None
    role = annotation or (resolve_role(name_pattern) if name_pattern else None)
    target_key = None
    if target_expr is not None:
        target_key = _resolve_call(prog, mod_index, dotted_to_rel, info,
                                   target_expr)
    return ThreadSpawn(
        src=info.src, lineno=call.lineno, in_class=info.class_name,
        name_pattern=name_pattern, role=role, annotation=annotation,
        target_key=target_key, unnamed=name_expr is None,
    )


def _assign_roles(prog: Program) -> None:
    """BFS role reachability from spawn targets + the public surface."""

    def reach(starts: set[FuncKey]) -> set[FuncKey]:
        seen = set(starts)
        stack = list(starts)
        while stack:
            k = stack.pop()
            for nxt in prog.edges.get(k, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append(nxt)
        return seen

    role_starts: dict[str, set[FuncKey]] = {}
    for spawn in prog.spawns:
        if spawn.role and spawn.target_key:
            role_starts.setdefault(spawn.role, set()).add(spawn.target_key)

    public = {
        k for k, f in prog.functions.items()
        if not f.name.startswith("_")
        or (f.name.startswith("__") and f.name.endswith("__"))
    }
    role_starts[CALLER_ROLE] = public

    for role, starts in role_starts.items():
        for k in reach(starts):
            prog.func_roles.setdefault(k, set()).add(role)


# ----------------------------------------------------------------- the rule


def check_thread_model(prog: Program) -> list[Finding]:
    findings: list[Finding] = []

    # --- rule 1: every spawn is named and registered
    for spawn in prog.spawns:
        if spawn.unnamed:
            f = spawn.src.finding(
                RULE_ROLE, spawn.lineno,
                "threading.Thread(...) without name= — anonymous threads "
                "are invisible to the role registry and every downstream "
                "concurrency rule; name it and register a role",
            )
        elif spawn.annotation and spawn.annotation not in ROLE_REGISTRY:
            f = spawn.src.finding(
                RULE_ROLE, spawn.lineno,
                f"# thread-role: {spawn.annotation} names a role outside "
                f"the bounded registry ({', '.join(sorted(ROLE_REGISTRY))})",
            )
        elif spawn.role is None:
            shown = spawn.name_pattern or "<dynamic>"
            f = spawn.src.finding(
                RULE_ROLE, spawn.lineno,
                f"thread name {shown!r} matches no pattern in the role "
                f"registry — add it to analysis/threads.py ROLE_REGISTRY "
                f"or annotate the spawn with # thread-role: <role>",
            )
        else:
            continue
        if f is not None:
            findings.append(f)

    # --- rule 2: cross-thread races on class attributes
    # group per (module, class): writes/reads by attr with role sets
    per_class: dict[tuple[str, str], dict[str, list[tuple[FuncInfo, int, bool]]]] = {}
    for info in prog.functions.values():
        if not info.class_name:
            continue
        if info.name in ("__init__", "__post_init__"):
            continue
        cls_key = (info.module, info.class_name)
        table = per_class.setdefault(cls_key, {})
        for attr, lines in info.writes.items():
            for ln in lines:
                table.setdefault(attr, []).append((info, ln, True))
        for attr, lines in info.reads.items():
            for ln in lines:
                table.setdefault(attr, []).append((info, ln, False))

    for (module, cls_name), table in sorted(per_class.items()):
        gc = prog.guarded.get((module, cls_name), GuardedClass(cls_name))
        src = next(
            (s for _t, s in prog.files if s.rel == module), None)
        if src is None:
            continue
        for attr, accesses in sorted(table.items()):
            if attr in gc.guarded:
                continue  # mutex-annotated: locks.py owns this attribute
            if attr in gc.thread_owned:
                owner = _owner_annotation(prog, module, cls_name, attr)
                allowed = THREAD_OWNER_ROLES.get(
                    owner or "", frozenset({CALLER_ROLE}))
                foreign = sorted({
                    r
                    for info, _ln, _w in accesses
                    for r in prog.roles_of(info.key)
                    if r not in allowed
                })
                if foreign:
                    first = min(
                        (ln for info, ln, _w in accesses
                         if prog.roles_of(info.key) - allowed),
                    )
                    f = src.finding(
                        RULE_RACE, first,
                        f"{cls_name}.{attr} is thread-owned "
                        f"(guarded-by: {owner}) but reachable from foreign "
                        f"role(s) {', '.join(foreign)} — thread ownership "
                        f"only holds if no other role can get here",
                    )
                    if f is not None:
                        findings.append(f)
                continue
            # unannotated: mutated from >= 2 roles?
            write_roles: set[str] = set()
            for info, _ln, is_write in accesses:
                if is_write:
                    write_roles |= prog.roles_of(info.key)
            if len(write_roles) >= 2:
                first = min(ln for _i, ln, w in accesses if w)
                f = src.finding(
                    RULE_RACE, first,
                    f"{cls_name}.{attr} mutated from roles "
                    f"{', '.join(sorted(write_roles))} with no guarded-by "
                    f"annotation — two threads can write it and no lock is "
                    f"declared; annotate it (and hold the lock) or confine "
                    f"it to one role",
                )
                if f is not None:
                    findings.append(f)
    return findings


def _owner_annotation(prog: Program, module: str, cls_name: str,
                      attr: str) -> Optional[str]:
    """Recover WHICH thread-lock annotation an attr carries (collect_guarded
    collapses them into one set)."""
    src = next((s for _t, s in prog.files if s.rel == module), None)
    if src is None:
        return None
    rx = re.compile(
        rf"self\.{re.escape(attr)}\s*[:=].*#\s*guarded-by:\s*([\w-]+)")
    for line in src.lines:
        m = rx.search(line)
        if m:
            return m.group(1)
    return None
