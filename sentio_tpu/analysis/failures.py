"""Failure-surface analysis: typed-error propagation, wire-contract audit,
frame-protocol exhaustiveness (whole-program).

Every chaos drill asserts "0 untyped errors" — but only for the paths the
drill exercises. This pass proves the property statically, reusing the
thread-role call graph from :mod:`.threads`:

* **untyped-boundary-escape** — raise sites are propagated up the call
  graph (filtered by enclosing ``try``/``except`` clauses, subclass-aware)
  to the declared *serving boundaries*: HTTP handlers (auto-discovered
  from ``app.router.add_get/add_post(...)`` registrations), and every
  thread spawned under a serving role (pump, supervisor, dispatcher, RPC
  handlers, accepter, status/telemetry loops, detached verify, drain,
  rebuild, health probes). A raise reachable at a boundary that is not a
  ``SentioError`` subclass is a finding anchored at the ORIGIN raise site.
  HTTP boundaries additionally allow ``SchemaError`` and aiohttp
  ``HTTP*`` exceptions (the middleware maps both deliberately).

* **typed-error-untyped-rethrow** — an ``except <SentioError subclass>``
  handler that raises a non-typed exception strips ``code`` /
  ``retry_after_s`` / ``soft_fail_exempt`` off the error on its way to
  the wire.

* **broad-except-swallow** — an ``except Exception`` whose body neither
  re-raises, nor logs, nor counts, and whose except line carries no
  written justification (``# noqa: BLE001 — <why>``) swallows failures
  invisibly. :mod:`.hygiene` owns the ``BaseException`` / bare-``except``
  variants; this rule covers exactly ``except Exception``.

* **codec-roundtrip** — the RPC exception codec
  (``runtime/worker.py:_encode_exc``/``_decode_exc``) resolves classes by
  name from ``sentio_tpu.infra.exceptions`` and re-raises through
  ``cls(message)``-shaped construction. A ``SentioError`` subclass
  defined in any other module silently degrades to ``RuntimeError``
  across the wire; one whose ``__init__`` requires extra positional
  arguments breaks the re-raise path. Both are findings at the class
  definition.

* **frame-kind-unhandled** / **frame-protocol** — transport channels are
  declared in source with ``# frame-emit: <channel> [via=pipe,socket]``
  (on a ``def`` or ``class`` line; class-level covers every method) and
  ``# frame-dispatch: <channel> via=pipe,socket``. Emitted frame kinds
  are extracted from ``send``-shaped calls (string literals or
  module-level constants); dispatched kinds from ``kind == ...`` /
  ``method in (...)`` comparisons. Every kind a channel can emit must
  have a dispatcher branch on every transport path (``via``) the channel
  serves — a frame kind added on one side only is a static finding, not
  a runtime ``FrameProtocolError``.

Like the thread model, the analysis is an under-approximation:
unresolvable dynamic raises/calls produce no findings, a transparent
re-raise (``except Exception: ...; raise``) is treated as catching (its
conditional re-raise cannot be modeled precisely), so every finding
corresponds to a concrete raise-to-boundary path. Missing edges cost
recall, never precision.
"""

from __future__ import annotations

import ast
import builtins
import re
from dataclasses import dataclass, field
from typing import Optional

from sentio_tpu.analysis.findings import Finding, SourceFile
from sentio_tpu.analysis.threads import FuncInfo, FuncKey, Program

__all__ = [
    "check_failures",
    "build_failure_graph",
    "collect_fault_points",
    "collect_armed_points",
    "FAILURE_RULE_IDS",
]

RULE_ESCAPE = "untyped-boundary-escape"
RULE_RETHROW = "typed-error-untyped-rethrow"
RULE_SWALLOW = "broad-except-swallow"
RULE_CODEC = "codec-roundtrip"
RULE_FRAME = "frame-kind-unhandled"
RULE_PROTO = "frame-protocol"

FAILURE_RULE_IDS = (RULE_ESCAPE, RULE_RETHROW, RULE_SWALLOW, RULE_CODEC,
                    RULE_FRAME, RULE_PROTO)

#: the typed-error root: everything transitively derived from it carries
#: the wire surface (code / status / details / retryable)
TYPED_ROOT = "SentioError"

#: the one module the RPC codec resolves exception classes from
CODEC_MODULE = "sentio_tpu/infra/exceptions.py"

#: spawn roles whose thread death IS a serving failure: an escape that
#: kills one of these silently degrades live traffic. Roles like warmup /
#: eval-worker / mock-api are bench-and-build scaffolding with their own
#: error handling and are deliberately out of scope.
SERVING_ROLES = frozenset({
    "pump", "supervisor", "dispatcher", "rpc", "accepter", "status",
    "telemetry", "detached-verify", "drain", "rebuild", "health-probe",
    "autoscaler",
})

#: boundaries that are not thread spawns or HTTP routes: the worker RPC
#: recv loop and the worker process entry points (qualname match, path
#: must end with the given suffix)
EXTRA_BOUNDARIES: tuple[tuple[str, str, str], ...] = (
    ("runtime/worker.py", "_WorkerServer.run", "worker RPC recv loop"),
    ("runtime/worker.py", "worker_main", "worker process entry"),
    ("runtime/worker.py", "worker_main_socket", "worker process entry"),
    ("runtime/worker.py", "worker_serve", "advertised-worker accept loop"),
)

#: aiohttp route registration methods (handler = last positional arg)
_ROUTE_ADDERS = frozenset({
    "add_get", "add_post", "add_put", "add_delete", "add_patch",
    "add_route",
})

#: BaseException-derived control flow `except Exception` cannot catch —
#: and which is never an untyped *failure* at a boundary (cancellation
#: and generator teardown are protocol, not errors)
_BASE_ONLY = frozenset({
    "KeyboardInterrupt", "SystemExit", "GeneratorExit", "CancelledError",
})
_NON_FAILURES = _BASE_ONLY | frozenset({"StopIteration", "StopAsyncIteration"})

#: builtin exception single-inheritance chains (everything else reaches
#: Exception implicitly, which the catch-all markers cover)
_BUILTIN_PARENTS = {
    "BrokenPipeError": "ConnectionError",
    "ConnectionResetError": "ConnectionError",
    "ConnectionAbortedError": "ConnectionError",
    "ConnectionRefusedError": "ConnectionError",
    "ConnectionError": "OSError",
    "FileNotFoundError": "OSError",
    "FileExistsError": "OSError",
    "PermissionError": "OSError",
    "InterruptedError": "OSError",
    "BlockingIOError": "OSError",
    "ChildProcessError": "OSError",
    "ProcessLookupError": "OSError",
    "NotADirectoryError": "OSError",
    "IsADirectoryError": "OSError",
    "TimeoutError": "OSError",
    "IOError": "OSError",
    "IndexError": "LookupError",
    "KeyError": "LookupError",
    "ZeroDivisionError": "ArithmeticError",
    "OverflowError": "ArithmeticError",
    "FloatingPointError": "ArithmeticError",
    "UnicodeDecodeError": "UnicodeError",
    "UnicodeEncodeError": "UnicodeError",
    "UnicodeTranslateError": "UnicodeError",
    "UnicodeError": "ValueError",
    "IndentationError": "SyntaxError",
    "RecursionError": "RuntimeError",
    "NotImplementedError": "RuntimeError",
    "ModuleNotFoundError": "ImportError",
}

_BUILTIN_EXCS = frozenset(
    n for n in dir(builtins)
    if isinstance(getattr(builtins, n), type)
    and issubclass(getattr(builtins, n), BaseException)
)

#: calls inside an `except Exception` body that count as handling it:
#: logging, traceback printing, or a metrics count
_SWALLOW_OK_CALLS = frozenset({
    "debug", "info", "warning", "error", "exception", "critical", "log",
    "print_exc", "format_exc", "print", "inc", "observe", "increment",
    "record", "record_worker_death", "note_stale_frame",
})

_NOQA_JUSTIFIED_RE = re.compile(r"#\s*noqa:\s*BLE001\b.*—\s*\S")

_FRAME_EMIT_RE = re.compile(
    r"#\s*frame-emit:\s*([\w-]+)(?:\s+via=([\w,]+))?")
_FRAME_DISPATCH_RE = re.compile(
    r"#\s*frame-dispatch:\s*([\w-]+)\s+via=([\w,]+)")
_FRAME_ANY_RE = re.compile(r"#\s*frame-(emit|dispatch):")

#: variables a dispatcher switches on — comparisons against anything else
#: are not dispatch branches
_DISPATCH_VARS = frozenset({"kind", "method"})

#: call shapes that put a frame on the wire; the kind position differs:
#: f(req_id, KIND, payload) vs f((req_id, KIND, payload)) vs _call(KIND, ..)
_SEND_ATTRS = frozenset({"send", "_send", "_send_frame"})


# ------------------------------------------------------------- typed universe


def _class_parents(prog: Program) -> dict[str, str]:
    """First resolvable base name per program class (single chains — the
    exception taxonomy is single-inheritance)."""
    parents: dict[str, str] = {}
    for name, defs in prog.classes.items():
        for _rel, node in defs:
            for base in node.bases:
                base_name = base.id if isinstance(base, ast.Name) else (
                    base.attr if isinstance(base, ast.Attribute) else None)
                if base_name:
                    parents.setdefault(name, base_name)
                    break
            if name in parents:
                break
    return parents


def _typed_universe(prog: Program, parents: dict[str, str]) -> set[str]:
    typed = {TYPED_ROOT}
    changed = True
    while changed:
        changed = False
        for name in prog.classes:
            if name not in typed and parents.get(name) in typed:
                typed.add(name)
                changed = True
    return typed


def _ancestor_chain(name: str, parents: dict[str, str]) -> list[str]:
    chain = []
    seen = set()
    n: Optional[str] = name
    while n and n not in seen:
        chain.append(n)
        seen.add(n)
        n = parents.get(n) or _BUILTIN_PARENTS.get(n)
    return chain


def _caught_by(exc_name: str, catches: frozenset,
               parents: dict[str, str]) -> bool:
    if "**" in catches:
        return True
    if "*" in catches and exc_name not in _BASE_ONLY:
        return True
    return any(a in catches for a in _ancestor_chain(exc_name, parents))


# ------------------------------------------------- per-function raise/call map


@dataclass
class _ExcSummary:
    #: (exception class name, raise lineno, enclosing catch filters)
    raises: list[tuple[str, int, tuple[frozenset, ...]]] = field(
        default_factory=list)
    #: (callee, call lineno, enclosing catch filters)
    calls: list[tuple[FuncKey, int, tuple[frozenset, ...]]] = field(
        default_factory=list)


def _handler_catch_names(handler: ast.ExceptHandler) -> list[str]:
    t = handler.type
    if t is None:
        return ["**"]
    names: list[str] = []
    elts = t.elts if isinstance(t, ast.Tuple) else [t]
    for e in elts:
        n = e.id if isinstance(e, ast.Name) else (
            e.attr if isinstance(e, ast.Attribute) else None)
        if n == "Exception":
            names.append("*")
        elif n == "BaseException":
            names.append("**")
        elif n:
            names.append(n)
    return names


def _handler_reraises(handler: ast.ExceptHandler) -> bool:
    """A bare ``raise`` anywhere in the handler body (nested defs
    excluded) makes the handler transparent for the escape analysis."""
    for node in _walk_body(handler.body):
        if isinstance(node, ast.Raise) and node.exc is None:
            return True
    return False


def _walk_body(stmts) -> list[ast.AST]:
    out: list[ast.AST] = []
    stack = list(stmts)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        out.append(node)
        stack.extend(ast.iter_child_nodes(node))
    return out


def _raise_class_name(node: ast.Raise, known: frozenset) -> Optional[str]:
    exc = node.exc
    if exc is None:
        return None  # bare re-raise: modeled by handler transparency
    if isinstance(exc, ast.Call):
        exc = exc.func
    name = exc.id if isinstance(exc, ast.Name) else (
        exc.attr if isinstance(exc, ast.Attribute) else None)
    # `raise exc` re-raising a bound variable resolves to a non-class
    # name; only names that are program classes or builtin exceptions are
    # concrete raise sites
    if name in known:
        return name
    return None


def _summarize(prog: Program, info: FuncInfo,
               known_classes: frozenset) -> _ExcSummary:
    summary = _ExcSummary()
    call_ids = {id(c) for c in info.calls}
    raw_calls: list[tuple[ast.Call, int, tuple[frozenset, ...]]] = []

    def visit(stmts, filters: tuple[frozenset, ...]) -> None:
        for node in stmts:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue
            if isinstance(node, ast.Raise):
                name = _raise_class_name(node, known_classes)
                if name is not None:
                    summary.raises.append((name, node.lineno, filters))
                for child in ast.iter_child_nodes(node):
                    visit([child], filters)
                continue
            if isinstance(node, (ast.Try, getattr(ast, "TryStar", ast.Try))):
                names: list[str] = []
                for h in node.handlers:
                    if not _handler_reraises(h):
                        names.extend(_handler_catch_names(h))
                body_filters = (filters + (frozenset(names),)
                                if names else filters)
                visit(node.body, body_filters)
                for h in node.handlers:
                    visit(h.body, filters)
                visit(node.orelse, filters)
                visit(node.finalbody, filters)
                continue
            if isinstance(node, ast.Call) and id(node) in call_ids:
                raw_calls.append((node, node.lineno, filters))
            for child in ast.iter_child_nodes(node):
                visit([child], filters)

    visit(list(ast.iter_child_nodes(info.node)), ())

    # resolve raw call nodes against the already-built call graph: every
    # edge out of this function is matched to the call sites sharing its
    # terminal name, so each site carries its own try/except filters
    edges = prog.edges.get(info.key, set())
    if edges:
        by_name: dict[str, list[FuncKey]] = {}
        for callee in edges:
            by_name.setdefault(callee[1].rsplit(".", 1)[-1], []).append(callee)
        for raw, lineno, filters in raw_calls:
            fn = raw.func
            name = fn.id if isinstance(fn, ast.Name) else (
                fn.attr if isinstance(fn, ast.Attribute) else None)
            for callee in by_name.get(name or "", ()):
                summary.calls.append((callee, lineno, filters))
    return summary


def _escape_sets(
    prog: Program,
    summaries: dict[FuncKey, _ExcSummary],
    parents: dict[str, str],
) -> dict[FuncKey, dict[str, tuple[str, int]]]:
    """Fixpoint: escapes(f) = local uncaught raises ∪ callee escapes not
    caught at the call site. Values map exception name → first origin
    ``(path, line)`` so findings anchor at the raise that started it."""
    escapes: dict[FuncKey, dict[str, tuple[str, int]]] = {}
    for key, s in summaries.items():
        local: dict[str, tuple[str, int]] = {}
        for name, lineno, filters in s.raises:
            if any(_caught_by(name, f, parents) for f in filters):
                continue
            local.setdefault(name, (key[0], lineno))
        escapes[key] = local
    changed = True
    rounds = 0
    while changed and rounds < 100:
        changed = False
        rounds += 1
        for key, s in summaries.items():
            mine = escapes[key]
            for callee, _lineno, filters in s.calls:
                for name, origin in escapes.get(callee, {}).items():
                    if name in mine:
                        continue
                    if any(_caught_by(name, f, parents) for f in filters):
                        continue
                    mine[name] = origin
                    changed = True
    return escapes


# ------------------------------------------------------------------ boundaries


@dataclass
class _Boundary:
    key: FuncKey
    kind: str            # human description ("pump thread", "http handler")
    allow_http: bool = False


def _discover_boundaries(prog: Program) -> list[_Boundary]:
    out: list[_Boundary] = []
    seen: set[FuncKey] = set()

    def add(key: FuncKey, kind: str, allow_http: bool = False) -> None:
        if key in prog.functions and key not in seen:
            seen.add(key)
            out.append(_Boundary(key=key, kind=kind, allow_http=allow_http))

    for spawn in prog.spawns:
        if spawn.role in SERVING_ROLES and spawn.target_key is not None:
            add(spawn.target_key, f"{spawn.role} thread")

    for tree, src in prog.files:
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _ROUTE_ADDERS
                    and node.args):
                continue
            handler = node.args[-1]
            if isinstance(handler, ast.Name):
                add((src.rel, handler.id), "http handler", allow_http=True)

    for suffix, qual, kind in EXTRA_BOUNDARIES:
        for key in prog.functions:
            if key[1] == qual and key[0].endswith(suffix):
                add(key, kind)
    return out


def _check_escapes(prog: Program, typed: set[str],
                   escapes: dict[FuncKey, dict[str, tuple[str, int]]],
                   boundaries: list[_Boundary]) -> list[Finding]:
    src_by_rel = {s.rel: s for _t, s in prog.files}
    # (origin path, origin line, exc name) -> [boundary descriptions]
    grouped: dict[tuple[str, int, str], list[str]] = {}
    for b in boundaries:
        for name, (opath, oline) in escapes.get(b.key, {}).items():
            if name in typed or name in _NON_FAILURES:
                continue
            if b.allow_http and (name.startswith("HTTP")
                                 or name == "SchemaError"):
                continue
            desc = f"{b.key[1]} ({b.kind})"
            grouped.setdefault((opath, oline, name), []).append(desc)
    findings: list[Finding] = []
    for (opath, oline, name), descs in sorted(grouped.items()):
        src = src_by_rel.get(opath)
        if src is None:
            continue
        shown = ", ".join(sorted(set(descs))[:3])
        more = len(set(descs)) - len(sorted(set(descs))[:3])
        if more > 0:
            shown += f" (+{more} more)"
        f = src.finding(
            RULE_ESCAPE, oline,
            f"raise {name} can reach serving boundary {shown} untyped — "
            f"wrap it in a SentioError subclass (typed status + "
            f"retry_after_s survive the wire) or catch it before the "
            f"boundary",
        )
        if f is not None:
            findings.append(f)
    return findings


# ----------------------------------------------- rethrow / swallow (per file)


def _check_handlers(prog: Program, typed: set[str]) -> list[Finding]:
    findings: list[Finding] = []
    known = frozenset(prog.classes) | _BUILTIN_EXCS
    for tree, src in prog.files:
        for node in ast.walk(tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            names = set(_handler_catch_names(node))
            catches_typed = bool(names & typed)
            body = _walk_body(node.body)
            if catches_typed:
                for stmt in body:
                    if not isinstance(stmt, ast.Raise) or stmt.exc is None:
                        continue
                    rname = _raise_class_name(stmt, known)
                    if rname is None or rname in typed:
                        continue
                    if rname in _NON_FAILURES or rname.startswith("HTTP"):
                        continue
                    f = src.finding(
                        RULE_RETHROW, stmt.lineno,
                        f"except {'/'.join(sorted(n for n in names if n not in ('*', '**')))} "
                        f"re-raises {rname}: the typed error's code / "
                        f"retry_after_s / soft_fail_exempt are lost on the "
                        f"way to the wire — re-raise the original or wrap "
                        f"it in a SentioError subclass",
                    )
                    if f is not None:
                        findings.append(f)
            if names == {"*"}:  # exactly `except Exception`
                handled = False
                for stmt in body:
                    if isinstance(stmt, ast.Raise):
                        handled = True
                        break
                    # counting the failure IS handling it (errors += 1)
                    if isinstance(stmt, ast.AugAssign):
                        handled = True
                        break
                    if isinstance(stmt, ast.Call):
                        fn = stmt.func
                        cname = fn.attr if isinstance(fn, ast.Attribute) \
                            else (fn.id if isinstance(fn, ast.Name) else "")
                        if cname in _SWALLOW_OK_CALLS:
                            handled = True
                            break
                # a handler that binds the exception and actually reads it
                # (records str(exc), maps it to a response, ...) consumed
                # the failure — only DROPPING the value is a swallow
                if not handled and node.name:
                    for sub in ast.walk(ast.Module(body=node.body,
                                                   type_ignores=[])):
                        if isinstance(sub, ast.Name) and sub.id == node.name:
                            handled = True
                            break
                if handled:
                    continue
                line = src.line_text(node.lineno)
                if _NOQA_JUSTIFIED_RE.search(line):
                    continue
                f = src.finding(
                    RULE_SWALLOW, node.lineno,
                    "except Exception swallows the failure without "
                    "re-raising typed, logging, or counting — handle it, "
                    "or justify the swallow in place "
                    "(# noqa: BLE001 — <why>)",
                )
                if f is not None:
                    findings.append(f)
    return findings


# ------------------------------------------------------------ codec roundtrip


def _check_codec(prog: Program, typed: set[str]) -> list[Finding]:
    src_by_rel = {s.rel: s for _t, s in prog.files}
    findings: list[Finding] = []
    for name in sorted(typed):
        if name == TYPED_ROOT:
            continue
        for rel, node in prog.classes.get(name, ()):
            src = src_by_rel.get(rel)
            if src is None:
                continue
            problems: list[str] = []
            if not rel.endswith(CODEC_MODULE):
                problems.append(
                    "defined outside sentio_tpu/infra/exceptions.py — "
                    "_decode_exc resolves subclasses by name from that "
                    "module only, so this type degrades to RuntimeError "
                    "across the RPC wire")
            init = prog.functions.get((rel, f"{name}.__init__"))
            if init is not None:
                bad = _ctor_incompatibility(init.node)
                if bad:
                    problems.append(bad)
            if problems:
                f = src.finding(
                    RULE_CODEC, node.lineno,
                    f"SentioError subclass {name} cannot round-trip the "
                    f"RPC exception codec: " + "; ".join(problems),
                )
                if f is not None:
                    findings.append(f)
    return findings


def _ctor_incompatibility(node: ast.AST) -> Optional[str]:
    """The codec's re-raise path (and the exhaustiveness gate) construct
    ``cls(message, **wire_kwargs)`` — more than one required positional
    parameter, or a required keyword-only one, breaks that."""
    args = node.args
    pos = list(args.posonlyargs) + list(args.args)
    required = len(pos) - len(args.defaults)
    if pos and pos[0].arg in ("self", "cls"):
        required -= 1
    if required > 1:
        return ("__init__ requires extra positional arguments beyond the "
                "message — the codec re-raise path constructs "
                "cls(message)")
    for kw, default in zip(args.kwonlyargs, args.kw_defaults):
        if default is None and kw.arg not in ("details", "kwargs"):
            return (f"__init__ requires keyword-only argument "
                    f"{kw.arg!r} — the codec re-raise path constructs "
                    f"cls(message)")
    return None


# ---------------------------------------------------- frame-kind exhaustiveness


@dataclass
class _Emitter:
    channel: str
    vias: Optional[frozenset]
    info: FuncInfo


@dataclass
class _Dispatcher:
    channel: str
    vias: frozenset
    info: FuncInfo
    kinds: set = field(default_factory=set)


def _annotation_lines(src: SourceFile, node: ast.AST) -> list[str]:
    return [src.line_text(node.lineno - 1), src.line_text(node.lineno)]


def _module_str_consts(tree: ast.Module) -> dict[str, str]:
    consts: dict[str, str] = {}
    for stmt in tree.body:
        if isinstance(stmt, ast.Assign) and isinstance(stmt.value, ast.Constant) \
                and isinstance(stmt.value.value, str):
            for t in stmt.targets:
                if isinstance(t, ast.Name):
                    consts[t.id] = stmt.value.value
    return consts


def _kind_consts(expr: ast.expr, consts: dict[str, str]) -> list[str]:
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return [expr.value]
    if isinstance(expr, ast.Name) and expr.id in consts:
        return [consts[expr.id]]
    if isinstance(expr, ast.IfExp):
        return (_kind_consts(expr.body, consts)
                + _kind_consts(expr.orelse, consts))
    if isinstance(expr, ast.Tuple):
        out = []
        for e in expr.elts:
            out.extend(_kind_consts(e, consts))
        return out
    return []


def _emitted_kinds(info: FuncInfo,
                   consts: dict[str, str]) -> list[tuple[str, int]]:
    """Frame kinds this function can put on the wire, with line numbers.
    Shapes: ``f(req_id, KIND, payload)`` (3+ positional args on a send
    attr), ``f((req_id, KIND, payload))`` (single 3-tuple arg), and
    ``self._call(KIND, ...)``."""
    out: list[tuple[str, int]] = []
    for call in info.calls:
        fn = call.func
        attr = fn.attr if isinstance(fn, ast.Attribute) else (
            fn.id if isinstance(fn, ast.Name) else None)
        if attr == "_call" and call.args:
            for k in _kind_consts(call.args[0], consts):
                out.append((k, call.lineno))
        elif attr in _SEND_ATTRS:
            if len(call.args) >= 3:
                for k in _kind_consts(call.args[1], consts):
                    out.append((k, call.lineno))
            elif (len(call.args) == 1 and isinstance(call.args[0], ast.Tuple)
                    and len(call.args[0].elts) == 3):
                for k in _kind_consts(call.args[0].elts[1], consts):
                    out.append((k, call.lineno))
    return out


def _dispatched_kinds(info: FuncInfo, consts: dict[str, str]) -> set:
    kinds: set = set()
    for node in _walk_body(info.node.body):
        if not isinstance(node, ast.Compare) or not node.ops:
            continue
        if not (isinstance(node.left, ast.Name)
                and node.left.id in _DISPATCH_VARS):
            continue
        if not isinstance(node.ops[0], (ast.Eq, ast.NotEq, ast.In, ast.NotIn)):
            continue
        for k in _kind_consts(node.comparators[0], consts):
            kinds.add(k)
    return kinds


def _check_frames(prog: Program) -> list[Finding]:
    findings: list[Finding] = []
    consts_by_rel = {src.rel: _module_str_consts(tree)
                     for tree, src in prog.files}
    emitters: list[_Emitter] = []
    dispatchers: list[_Dispatcher] = []

    # class-level annotations fan out to every method (qualname prefix)
    class_emit: dict[tuple[str, str], tuple[str, Optional[frozenset]]] = {}
    for cname, defs in prog.classes.items():
        for rel, node in defs:
            src = next((s for _t, s in prog.files if s.rel == rel), None)
            if src is None:
                continue
            for line in _annotation_lines(src, node):
                m = _FRAME_EMIT_RE.search(line)
                if m:
                    vias = (frozenset(m.group(2).split(","))
                            if m.group(2) else None)
                    class_emit[(rel, cname)] = (m.group(1), vias)

    for key, info in prog.functions.items():
        src = info.src
        func_emits = False
        for line in _annotation_lines(src, info.node):
            matched = False
            m = _FRAME_EMIT_RE.search(line)
            if m:
                vias = (frozenset(m.group(2).split(","))
                        if m.group(2) else None)
                emitters.append(_Emitter(m.group(1), vias, info))
                func_emits = matched = True
            md = _FRAME_DISPATCH_RE.search(line)
            if md:
                d = _Dispatcher(md.group(1),
                                frozenset(md.group(2).split(",")), info)
                d.kinds = _dispatched_kinds(
                    info, consts_by_rel.get(info.module, {}))
                dispatchers.append(d)
                matched = True
            if _FRAME_ANY_RE.search(line) and not matched:
                f = src.finding(
                    RULE_PROTO, info.node.lineno,
                    "malformed frame annotation — expected "
                    "'# frame-emit: <channel> [via=a,b]' or "
                    "'# frame-dispatch: <channel> via=a,b'",
                )
                if f is not None:
                    findings.append(f)
        if not func_emits:
            top_cls = key[1].split(".", 1)[0]
            ce = class_emit.get((info.module, top_cls))
            if ce is not None and "." in key[1]:
                emitters.append(_Emitter(ce[0], ce[1], info))

    by_channel_disp: dict[str, list[_Dispatcher]] = {}
    for d in dispatchers:
        by_channel_disp.setdefault(d.channel, []).append(d)

    # emitted kinds per channel, deduped to the first emit site
    emitted: dict[tuple[str, str], tuple[FuncInfo, int, Optional[frozenset]]] = {}
    for e in emitters:
        consts = consts_by_rel.get(e.info.module, {})
        for kind, lineno in _emitted_kinds(e.info, consts):
            cur = emitted.get((e.channel, kind))
            site = (e.info, lineno, e.vias)
            if cur is None or (e.info.module, lineno) < (cur[0].module, cur[1]):
                emitted[(e.channel, kind)] = site

    for (channel, kind), (info, lineno, evias) in sorted(
            emitted.items(), key=lambda kv: (kv[0], kv[1][0].module, kv[1][1])):
        disp = by_channel_disp.get(channel, [])
        if not disp:
            f = info.src.finding(
                RULE_FRAME, lineno,
                f"channel {channel!r} emits frame kind {kind!r} but has no "
                f"# frame-dispatch annotation anywhere in the program",
            )
            if f is not None:
                findings.append(f)
            continue
        channel_vias = frozenset().union(*(d.vias for d in disp))
        vias = evias if evias is not None else channel_vias
        for via in sorted(vias - channel_vias):
            f = info.src.finding(
                RULE_PROTO, lineno,
                f"frame kind {kind!r} declares via={via} but no dispatcher "
                f"on channel {channel!r} serves that path",
            )
            if f is not None:
                findings.append(f)
        missing = sorted(
            via for via in (vias & channel_vias)
            if not any(via in d.vias and kind in d.kinds for d in disp)
        )
        if missing:
            served_by = ", ".join(sorted(d.info.key[1] for d in disp))
            f = info.src.finding(
                RULE_FRAME, lineno,
                f"frame kind {kind!r} (channel {channel!r}) has no "
                f"dispatcher branch on the {'/'.join(missing)} receive "
                f"path — a one-sided frame kind is a runtime "
                f"FrameProtocolError waiting to happen (dispatchers: "
                f"{served_by})",
            )
            if f is not None:
                findings.append(f)
    return findings


# -------------------------------------------------------------------- the rule


def check_failures(prog: Program) -> list[Finding]:
    parents = _class_parents(prog)
    typed = _typed_universe(prog, parents)
    known = frozenset(prog.classes) | _BUILTIN_EXCS
    summaries = {key: _summarize(prog, info, known)
                 for key, info in prog.functions.items()}
    escapes = _escape_sets(prog, summaries, parents)
    boundaries = _discover_boundaries(prog)
    findings = _check_escapes(prog, typed, escapes, boundaries)
    findings.extend(_check_handlers(prog, typed))
    findings.extend(_check_codec(prog, typed))
    findings.extend(_check_frames(prog))
    return findings


# -------------------------------------------------------- boundary graph dump


def build_failure_graph(prog: Program) -> dict:
    """JSON view of the failure surface (``sentio lint --boundary-graph``):
    every serving boundary with the exception names that can escape to it
    (typed and untyped, with origins), plus the frame channels."""
    parents = _class_parents(prog)
    typed = _typed_universe(prog, parents)
    known = frozenset(prog.classes) | _BUILTIN_EXCS
    summaries = {key: _summarize(prog, info, known)
                 for key, info in prog.functions.items()}
    escapes = _escape_sets(prog, summaries, parents)
    boundaries = _discover_boundaries(prog)

    out_boundaries = []
    for b in sorted(boundaries, key=lambda b: (b.key[0], b.key[1])):
        info = prog.functions[b.key]
        esc = {}
        for name, (opath, oline) in sorted(escapes.get(b.key, {}).items()):
            esc[name] = {
                "origin": f"{opath}:{oline}",
                "typed": name in typed,
            }
        out_boundaries.append({
            "qualname": b.key[1],
            "path": b.key[0],
            "line": info.node.lineno,
            "kind": b.kind,
            "escapes": esc,
        })

    consts_by_rel = {src.rel: _module_str_consts(tree)
                     for tree, src in prog.files}
    channels: dict[str, dict] = {}
    for key, info in prog.functions.items():
        for line in _annotation_lines(info.src, info.node):
            md = _FRAME_DISPATCH_RE.search(line)
            if md:
                ch = channels.setdefault(
                    md.group(1), {"emits": {}, "dispatchers": []})
                ch["dispatchers"].append({
                    "qualname": key[1],
                    "path": key[0],
                    "vias": sorted(md.group(2).split(",")),
                    "handles": sorted(_dispatched_kinds(
                        info, consts_by_rel.get(info.module, {}))),
                })
    # reuse the emitter fan-out from the checker by re-walking annotations
    class_emit: dict[tuple[str, str], str] = {}
    for cname, defs in prog.classes.items():
        for rel, node in defs:
            src = next((s for _t, s in prog.files if s.rel == rel), None)
            if src is None:
                continue
            for line in _annotation_lines(src, node):
                m = _FRAME_EMIT_RE.search(line)
                if m:
                    class_emit[(rel, cname)] = m.group(1)
    for key, info in prog.functions.items():
        channel = None
        for line in _annotation_lines(info.src, info.node):
            m = _FRAME_EMIT_RE.search(line)
            if m:
                channel = m.group(1)
        if channel is None and "." in key[1]:
            channel = class_emit.get((info.module, key[1].split(".", 1)[0]))
        if channel is None:
            continue
        ch = channels.setdefault(channel, {"emits": {}, "dispatchers": []})
        consts = consts_by_rel.get(info.module, {})
        for kind, lineno in _emitted_kinds(info, consts):
            ch["emits"].setdefault(kind, []).append(f"{key[0]}:{lineno}")
    for ch in channels.values():
        ch["emits"] = {k: sorted(v) for k, v in sorted(ch["emits"].items())}
        ch["dispatchers"].sort(key=lambda d: (d["path"], d["qualname"]))

    return {
        "typed": sorted(typed),
        "boundaries": out_boundaries,
        "channels": dict(sorted(channels.items())),
    }


# ------------------------------------------------------- fault-point crossref


def collect_fault_points(
    files: list[tuple[ast.Module, SourceFile]],
) -> dict[str, list[str]]:
    """Every ``faults.hit("<name>")`` / ``hit_frame`` injection point in
    the tree → plant sites. ``SocketTransport._hit("send"/"recv")`` plants
    the dynamic ``transport.<op>[.<scope>]`` family — recorded under its
    static ``transport.<op>`` base name."""
    points: dict[str, list[str]] = {}
    for tree, src in files:
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call) and node.args):
                continue
            fn = node.func
            attr = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else None)
            arg = node.args[0]
            if not (isinstance(arg, ast.Constant)
                    and isinstance(arg.value, str)):
                continue
            if attr in ("hit", "hit_frame"):
                points.setdefault(arg.value, []).append(
                    f"{src.rel}:{node.lineno}")
            elif attr == "_hit":
                points.setdefault(f"transport.{arg.value}", []).append(
                    f"{src.rel}:{node.lineno}")
    return {k: sorted(v) for k, v in sorted(points.items())}


def collect_armed_points(
    files: list[tuple[ast.Module, SourceFile]],
) -> dict[str, list[str]]:
    """Every fault point a test or bench mode arms: ``faults.arm(...)``,
    ``faults.inject(...)`` context managers, and worker-RPC
    ``inject_fault(...)`` calls. Scoped arms (``transport.recv.r0``)
    count toward their ``transport.recv`` base point."""
    armed: dict[str, list[str]] = {}
    for tree, src in files:
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call) and node.args):
                continue
            fn = node.func
            attr = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else None)
            if attr not in ("arm", "inject", "inject_fault"):
                continue
            arg = node.args[0]
            if not (isinstance(arg, ast.Constant)
                    and isinstance(arg.value, str)):
                continue
            name = arg.value
            # scoped arms ("transport.recv.r0") credit their base point
            if name.count(".") >= 2:
                name = ".".join(name.split(".")[:2])
            armed.setdefault(name, []).append(
                f"{src.rel}:{node.lineno}")
    return {k: sorted(v) for k, v in sorted(armed.items())}


def fault_point_inventory() -> dict:
    """The committed chaos-coverage map (``analysis/fault_points.json``):
    every injection point planted in the package, and the test/bench files
    that arm it. File-level (line numbers churn too fast to commit); the
    tier-1 cross-reference test regenerates and compares."""
    import json as _json  # noqa: F401 — re-exported for the __main__ dump

    from sentio_tpu.analysis.runner import PACKAGE_ROOT, REPO_ROOT, parse_paths

    pkg, _errs = parse_paths([PACKAGE_ROOT])
    arming_roots = [REPO_ROOT / "tests", REPO_ROOT / "bench.py"]
    tests, _errs = parse_paths([p for p in arming_roots if p.exists()])
    points = collect_fault_points(pkg)
    armed = collect_armed_points(tests)
    return {
        "points": {k: sorted({s.rsplit(":", 1)[0] for s in v})
                   for k, v in points.items()},
        "armed_by": {k: sorted({s.rsplit(":", 1)[0] for s in v})
                     for k, v in armed.items() if k in points},
    }


if __name__ == "__main__":  # pragma: no cover — `python -m ...failures`
    import json

    print(json.dumps(fault_point_inventory(), indent=1))
