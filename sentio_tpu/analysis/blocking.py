"""Blocking-call discipline: unbounded joins + supervisor-thread waits.

The stall-tolerance layer (runtime/service.py heartbeat + the ReplicaSet
watchdog) exists because a thread wedged inside a blocking call raises
nothing. These rules keep the *framework's own* threads from recreating the
hazard they guard against:

``join-no-timeout``
    A zero-argument ``.join()`` call blocks forever if the joined thread is
    wedged (the exact failure mode the watchdog detects in pumps). Every
    thread join in framework code must carry a timeout and surface the
    straggler — ``PagedGenerationService.close()`` counting ``pump_leaked``
    is the pattern. Zero-argument only: ``"sep".join(parts)`` and
    ``os.path.join(a, b)`` take positional arguments and never match.

``supervisor-blocking-wait``
    Inside supervisor/watchdog-owned code (methods or functions whose name
    contains ``supervise``, ``supervisor``, ``watchdog``, or
    ``rebuild_worker``, and their nested functions), a zero-argument
    ``.wait()`` or ``.get()`` blocks the detection loop itself — a stalled
    supervisor cannot quarantine anything. Waits there must carry a timeout
    so the loop keeps its cadence. Zero-argument only: ``event.wait(0.5)``
    and ``d.get(key)`` never match.

Suppression: the standard inline ``# lint: allow(<rule>)`` marker.
"""

from __future__ import annotations

import ast
import re

from sentio_tpu.analysis.findings import Finding, SourceFile

__all__ = ["check_blocking"]

RULE_JOIN = "join-no-timeout"
RULE_SUPERVISOR_WAIT = "supervisor-blocking-wait"

# function/method names that mark supervisor- or watchdog-owned code paths
_SUPERVISOR_NAME = re.compile(r"supervise|supervisor|watchdog|rebuild_worker")

# zero-argument attribute calls that block forever on these names
_BLOCKING_ATTRS = ("wait", "get")


def _zero_arg_attr_call(node: ast.Call) -> str:
    """The attribute name of a ``obj.attr()`` call with NO arguments at
    all, else ''."""
    if node.args or node.keywords:
        return ""
    if isinstance(node.func, ast.Attribute):
        return node.func.attr
    return ""


def check_blocking(tree: ast.Module, src: SourceFile) -> list[Finding]:
    findings: list[Finding] = []

    def visit(node: ast.AST, in_supervisor: bool) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            inner = in_supervisor or bool(_SUPERVISOR_NAME.search(node.name))
            for child in ast.iter_child_nodes(node):
                visit(child, inner)
            return
        if isinstance(node, ast.Call):
            attr = _zero_arg_attr_call(node)
            if attr == "join":
                f = src.finding(
                    RULE_JOIN, node.lineno,
                    ".join() without a timeout blocks forever on a wedged "
                    "thread — pass timeout= and surface the straggler "
                    "(see PagedGenerationService.close pump_leaked)",
                )
                if f is not None:
                    findings.append(f)
            elif in_supervisor and attr in _BLOCKING_ATTRS:
                f = src.finding(
                    RULE_SUPERVISOR_WAIT, node.lineno,
                    f".{attr}() without a timeout inside supervisor/"
                    "watchdog-owned code — a blocked detection loop cannot "
                    "quarantine anything; poll with a timeout instead",
                )
                if f is not None:
                    findings.append(f)
        for child in ast.iter_child_nodes(node):
            visit(child, in_supervisor)

    visit(tree, False)
    return findings
