"""Finding model + baseline ratchet for ``sentio lint``.

A finding is keyed for baseline matching by ``(rule, path, context)`` where
``context`` is the stripped source line — NOT the line number, so findings
survive unrelated edits above them. The baseline is a committed JSON list;
the gate fails only on findings absent from the baseline (ratchet: fixing a
baselined finding makes its entry stale, and ``--update-baseline`` prunes
it — the file only ever shrinks unless a human deliberately re-records).
"""

from __future__ import annotations

import json
import re
from collections import Counter
from dataclasses import dataclass, field
from pathlib import Path
from typing import Iterable, Optional

__all__ = [
    "Finding",
    "SourceFile",
    "load_baseline",
    "save_baseline",
    "diff_baseline",
]

_ALLOW_RE = re.compile(r"#\s*lint:\s*allow\(([\w,\s-]+)\)")
_WALL_CLOCK_RE = re.compile(r"#\s*wall-clock\b")
_GUARDED_RE = re.compile(r"#\s*guarded-by:\s*([\w.-]+)")
_LOCK_HELD_RE = re.compile(r"#\s*lock-held:\s*([\w.-]+)")


@dataclass(frozen=True)
class Finding:
    """One lint finding. ``context`` is the stripped source line at
    ``line`` — the stable half of the baseline key."""

    rule: str
    path: str  # repo-relative, forward slashes
    line: int
    message: str
    context: str = ""

    @property
    def key(self) -> tuple[str, str, str]:
        return (self.rule, self.path, self.context)

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"

    def to_json(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "context": self.context,
            "message": self.message,
        }


@dataclass
class SourceFile:
    """Parsed view of one file shared by every rule: source text, physical
    lines, and the per-line annotation maps (allow / wall-clock /
    guarded-by / lock-held)."""

    path: Path
    rel: str
    text: str
    lines: list[str] = field(default_factory=list)

    def __post_init__(self) -> None:
        if not self.lines:
            self.lines = self.text.splitlines()

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def allows(self, lineno: int, rule: str) -> bool:
        m = _ALLOW_RE.search(self.line_text(lineno))
        if not m:
            return False
        allowed = {r.strip() for r in m.group(1).split(",")}
        return rule in allowed

    def wall_clock_ok(self, lineno: int) -> bool:
        """``# wall-clock:`` on the line or the line above (annotations on
        multi-line expressions land where the comment physically fits)."""
        return bool(
            _WALL_CLOCK_RE.search(self.line_text(lineno))
            or _WALL_CLOCK_RE.search(self.line_text(lineno - 1))
        )

    def guarded_by(self, lineno: int) -> Optional[str]:
        m = _GUARDED_RE.search(self.line_text(lineno))
        return m.group(1) if m else None

    def lock_held_marker(self, lineno: int) -> Optional[str]:
        m = _LOCK_HELD_RE.search(self.line_text(lineno))
        return m.group(1) if m else None

    def finding(self, rule: str, lineno: int, message: str) -> Optional[Finding]:
        """Build a finding unless an inline allow suppresses it."""
        if self.allows(lineno, rule):
            return None
        return Finding(
            rule=rule,
            path=self.rel,
            line=lineno,
            message=message,
            context=self.line_text(lineno).strip(),
        )


# ------------------------------------------------------------------ baseline


def load_baseline(path: str | Path) -> list[dict]:
    p = Path(path)
    if not p.exists():
        return []
    data = json.loads(p.read_text())
    if not isinstance(data, list):
        raise ValueError(f"baseline {p} must be a JSON list")
    return data


def save_baseline(path: str | Path, findings: Iterable[Finding],
                  keep_why_from: Iterable[dict] = ()) -> None:
    """Rewrite the baseline. ``keep_why_from`` (usually the PREVIOUS
    baseline) carries per-entry ``"why"`` justifications forward so
    ``--update-baseline`` never strips a written triage."""
    why_by_key = {
        (e.get("rule"), e.get("path"), e.get("context", "")): e["why"]
        for e in keep_why_from
        if e.get("why")
    }
    entries = []
    for f in findings:
        e = f.to_json()
        why = why_by_key.get(f.key)
        if why:
            e["why"] = why
        entries.append(e)
    entries.sort(key=lambda e: (e["path"], e["rule"], e["context"]))
    Path(path).write_text(json.dumps(entries, indent=1) + "\n")


def diff_baseline(
    findings: list[Finding], baseline: list[dict]
) -> tuple[list[Finding], list[Finding], list[dict]]:
    """→ ``(new, matched, stale)``. Matching is by ``(rule, path, context)``
    with multiplicity: two identical findings need two baseline entries."""
    budget: Counter = Counter(
        (e["rule"], e["path"], e.get("context", "")) for e in baseline
    )
    new: list[Finding] = []
    matched: list[Finding] = []
    for f in findings:
        if budget.get(f.key, 0) > 0:
            budget[f.key] -= 1
            matched.append(f)
        else:
            new.append(f)
    stale = [
        {"rule": r, "path": p, "context": c}
        for (r, p, c), n in budget.items()
        for _ in range(n)
        if n > 0
    ]
    return new, matched, stale
