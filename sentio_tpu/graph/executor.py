"""Minimal typed DAG executor — the framework's LangGraph replacement.

The reference assembles its pipeline as a LangGraph ``StateGraph`` with
conditional edges (/root/reference/src/core/graph/factory.py:94-188). We need
the same shape — named nodes over a shared state, static and conditional
edges, sync + async invocation — but with zero external deps and with stage
boundaries that double as host/TPU dispatch points (a node is free to await a
batched device call). Nodes return *partial* state updates; the executor
merges them, records per-node wall time, and never lets a node exception kill
the pipeline unless the node opts out of soft-fail.

Trace context: when ``metadata["query_id"]`` is set (the serving layer's
request id), the executor publishes the finished run's per-node timings and
path into the flight recorder (infra/flight.py), joining the graph stage
timeline with the decode engine's tick events under one id.
"""

from __future__ import annotations

import asyncio
import inspect
import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Awaitable, Callable, Mapping, Optional, Union

from sentio_tpu.infra.exceptions import GraphError

logger = logging.getLogger(__name__)

END = "__end__"

# live detached-node threads (async verify): bench/eval/tests join them via
# wait_detached() before tearing the decode service down under their feet
_detached_lock = threading.Lock()
_detached_threads: list[threading.Thread] = []  # guarded-by: _detached_lock


def wait_detached(timeout_s: float = 30.0) -> bool:
    """Join every live detached-node thread (best effort, bounded by the
    shared ``timeout_s`` wall clock). Returns whether all finished. The
    serving path never calls this — detached nodes are fire-and-forget
    there — but anything that closes the decode service right after a
    graph run (bench sweeps, eval, tests) must, or the trailing verify
    decode races the shutdown."""
    deadline = time.perf_counter() + max(timeout_s, 0.0)
    while True:
        with _detached_lock:
            _detached_threads[:] = [t for t in _detached_threads if t.is_alive()]
            live = list(_detached_threads)
        if not live:
            return True
        if time.perf_counter() >= deadline:
            return False
        live[0].join(timeout=min(max(deadline - time.perf_counter(), 0.0), 0.5))

NodeFn = Callable[[dict], Union[Mapping[str, Any], Awaitable[Mapping[str, Any]], None]]
RouterFn = Callable[[dict], str]


@dataclass
class _Node:
    name: str
    fn: NodeFn
    soft_fail: bool = True
    # detached nodes run OFF the critical path: the executor snapshots the
    # state, launches the node on a daemon thread, stamps
    # metadata[f"{name}_pending"] = True, and follows the edge immediately.
    # The node's return value is discarded — a detached node communicates
    # through side effects (the async verify node writes its verdict to the
    # flight recorder, where /debug/flight/{id} serves it)
    detached: bool = False


@dataclass
class CompiledGraph:
    """An immutable, runnable pipeline. Build via :class:`GraphBuilder`."""

    nodes: dict[str, _Node]
    edges: dict[str, Union[str, RouterFn]]
    entry: str
    max_steps: int = 64

    async def ainvoke(self, state: dict, config: Optional[dict] = None) -> dict:
        state = dict(state)
        meta = dict(state.get("metadata", {}))
        if config:
            meta.setdefault("graph_config", dict(config))
        state["metadata"] = meta

        # OTel node spans (infra/tracing.py): resolved once per run; the
        # single `enabled` bool keeps the default (tracing-off) path free
        # of any span or context-manager overhead per node
        from sentio_tpu.infra.tracing import get_tracing

        tracing = get_tracing()

        current = self.entry
        steps = 0
        path: list[str] = []
        while current != END:
            if current not in self.nodes:
                raise GraphError(f"unknown node {current!r} (path so far: {path})")
            steps += 1
            if steps > self.max_steps:
                raise GraphError(f"step limit {self.max_steps} exceeded; path: {path}")
            node = self.nodes[current]
            path.append(current)
            if node.detached:
                # off-critical-path stage (async verify): snapshot the state
                # so the thread never races later merges, launch, move on.
                # The answer does not wait for the audit — this edge is what
                # turns verify's ~500 ms from blocking latency into overlap.
                snapshot = dict(state)
                snapshot["metadata"] = dict(state.get("metadata", {}))
                thread = threading.Thread(
                    target=_run_detached, args=(node, snapshot),
                    name=f"graph-detached-{node.name}", daemon=True,
                )
                with _detached_lock:
                    _detached_threads[:] = [
                        t for t in _detached_threads if t.is_alive()
                    ]
                    _detached_threads.append(thread)
                thread.start()
                state = _merge(
                    state, {"metadata": {f"{node.name}_pending": True}}
                )
                edge = self.edges.get(current, END)
                current = edge(state) if callable(edge) else edge
                continue
            t0 = time.perf_counter()
            try:
                if tracing.enabled:
                    # span per node, carrying the trace id and (once the
                    # generate node stamped it) the serving replica — the
                    # correlation keys that join graph spans to flight
                    # ticks and XLA step annotations
                    with tracing.span(
                        f"graph.{node.name}",
                        request_id=str(meta.get("query_id", "")),
                        replica_id=int(state["metadata"].get("replica_id", -1)),
                    ):
                        update = node.fn(state)
                        if inspect.isawaitable(update):
                            update = await update
                else:
                    update = node.fn(state)
                    if inspect.isawaitable(update):
                        update = await update
            except Exception as exc:  # noqa: BLE001 — soft-fail ladder by design
                # typed shed/deadline errors opt OUT of soft-fail: turning a
                # 429/503/504 into a degraded 200 would hide overload from
                # the caller, whose retry-elsewhere is the correct response
                if not node.soft_fail or getattr(exc, "soft_fail_exempt", False):
                    raise
                logger.exception("node %s failed softly", node.name)
                update = {"metadata": {f"{node.name}_error": str(exc)}}
            dt_ms = (time.perf_counter() - t0) * 1000.0
            state = _merge(state, update)
            timings = dict(state["metadata"].get("node_timings_ms", {}))
            timings[node.name] = round(timings.get(node.name, 0.0) + dt_ms, 3)
            state["metadata"]["node_timings_ms"] = timings

            edge = self.edges.get(current, END)
            current = edge(state) if callable(edge) else edge
        state["metadata"]["graph_path"] = path
        request_id = state["metadata"].get("query_id")
        if request_id:
            try:
                from sentio_tpu.infra.flight import get_flight_recorder

                get_flight_recorder().add_node_timings(
                    str(request_id),
                    state["metadata"].get("node_timings_ms", {}),
                    graph_path=path,
                )
            except Exception:  # noqa: BLE001 — telemetry must not fail runs
                logger.debug("flight recording failed", exc_info=True)
        return state

    def invoke(self, state: dict, config: Optional[dict] = None) -> dict:
        """Sync entry point. Safe to call when no event loop is running."""
        return asyncio.run(self.ainvoke(state, config))


def _run_detached(node: _Node, state: dict) -> None:
    """Drive one detached node to completion on its own thread (its own
    event loop — the spawning loop is long gone by the time a slow audit
    decode finishes). Exceptions are logged, never propagated: the caller
    already has its answer."""
    from sentio_tpu.infra.tracing import get_tracing

    tracing = get_tracing()
    try:
        if tracing.enabled:
            with tracing.span(
                f"graph.{node.name}", detached=True,
                request_id=str(state.get("metadata", {}).get("query_id", "")),
                replica_id=int(
                    state.get("metadata", {}).get("replica_id", -1)),
            ):
                update = node.fn(state)
                if inspect.isawaitable(update):
                    asyncio.run(_await_detached(update))
            return
        update = node.fn(state)
        if inspect.isawaitable(update):
            asyncio.run(_await_detached(update))
    except Exception:  # noqa: BLE001 — off-path stage must not crash anything
        logger.exception("detached node %s failed", node.name)


async def _await_detached(awaitable) -> None:
    await awaitable


def _merge(state: dict, update: Optional[Mapping[str, Any]]) -> dict:
    if not update:
        return state
    new = dict(state)
    for key, value in update.items():
        if key == "metadata" and isinstance(value, Mapping):
            meta = dict(new.get("metadata", {}))
            meta.update(value)
            new["metadata"] = meta
        else:
            new[key] = value
    return new


@dataclass
class GraphBuilder:
    """Fluent builder mirroring the reference's StateGraph assembly surface:
    ``add_node`` / ``add_edge`` / ``add_conditional_edge`` / ``set_entry``."""

    _nodes: dict[str, _Node] = field(default_factory=dict)
    _edges: dict[str, Union[str, RouterFn]] = field(default_factory=dict)
    _entry: Optional[str] = None
    max_steps: int = 64

    def add_node(self, name: str, fn: NodeFn, soft_fail: bool = True,
                 detached: bool = False) -> "GraphBuilder":
        if name == END:
            raise GraphError(f"{END!r} is reserved")
        if name in self._nodes:
            raise GraphError(f"duplicate node {name!r}")
        self._nodes[name] = _Node(name, fn, soft_fail, detached)
        return self

    def add_edge(self, src: str, dst: str) -> "GraphBuilder":
        self._edges[src] = dst
        return self

    def add_conditional_edge(self, src: str, router: RouterFn) -> "GraphBuilder":
        self._edges[src] = router
        return self

    def set_entry(self, name: str) -> "GraphBuilder":
        self._entry = name
        return self

    def compile(self) -> CompiledGraph:
        if not self._entry:
            raise GraphError("no entry point set")
        if self._entry not in self._nodes:
            raise GraphError(f"entry {self._entry!r} is not a node")
        for src, edge in self._edges.items():
            if src not in self._nodes:
                raise GraphError(f"edge from unknown node {src!r}")
            if isinstance(edge, str) and edge != END and edge not in self._nodes:
                raise GraphError(f"edge to unknown node {edge!r}")
        return CompiledGraph(
            nodes=dict(self._nodes),
            edges=dict(self._edges),
            entry=self._entry,
            max_steps=self.max_steps,
        )
