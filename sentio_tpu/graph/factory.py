"""Graph assembly: conditional pipeline construction from config.

Parity with /root/reference/src/core/graph/factory.py:28-208 (``GraphConfig``
with USE_RERANKER / USE_VERIFIER toggles, ``build_basic_graph``,
``build_streaming_graph``) on our own executor — stage boundaries double as
host/TPU dispatch points. The conditional edges mirror the reference's:
retrieve → [rerank] → select → generate → [verify] → END.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from sentio_tpu.config import Settings, get_settings
from sentio_tpu.graph.executor import END, CompiledGraph, GraphBuilder
from sentio_tpu.graph.nodes import (
    create_document_selector_node,
    create_generator_node,
    create_reranker_node,
    create_retriever_node,
    create_verifier_node,
)


@dataclass
class GraphConfig:
    use_reranker: bool = True
    use_verifier: bool = True
    settings: Settings = field(default_factory=get_settings)

    @classmethod
    def from_settings(cls, settings: Optional[Settings] = None) -> "GraphConfig":
        settings = settings or get_settings()
        return cls(
            use_reranker=settings.rerank.enabled,
            use_verifier=settings.generator.use_verifier,
            settings=settings,
        )


def build_basic_graph(
    retriever,
    generator,
    reranker=None,
    verifier=None,
    config: Optional[GraphConfig] = None,
) -> CompiledGraph:
    config = config or GraphConfig.from_settings()
    settings = config.settings
    builder = GraphBuilder()

    builder.add_node("retrieve", create_retriever_node(retriever, settings))
    use_rerank = config.use_reranker and reranker is not None
    if use_rerank:
        builder.add_node("rerank", create_reranker_node(reranker, settings))
    builder.add_node("select", create_document_selector_node(settings))
    builder.add_node("generate", create_generator_node(generator, settings))
    use_verify = config.use_verifier and verifier is not None
    if use_verify:
        builder.add_node("verify", create_verifier_node(verifier, settings))

    builder.set_entry("retrieve")
    builder.add_edge("retrieve", "rerank" if use_rerank else "select")
    if use_rerank:
        builder.add_edge("rerank", "select")
    builder.add_edge("select", "generate")
    builder.add_edge("generate", "verify" if use_verify else END)
    if use_verify:
        builder.add_edge("verify", END)
    return builder.compile()


def build_streaming_graph(*args, **kwargs) -> CompiledGraph:
    """Streaming runs the same pipeline; the serving layer streams the
    generator stage directly (the reference's alias, factory.py:191-208)."""
    return build_basic_graph(*args, **kwargs)
