"""Graph assembly: conditional pipeline construction from config.

Parity with /root/reference/src/core/graph/factory.py:28-208 (``GraphConfig``
with USE_RERANKER / USE_VERIFIER toggles, ``build_basic_graph``,
``build_streaming_graph``) on our own executor — stage boundaries double as
host/TPU dispatch points. The conditional edges mirror the reference's:
retrieve → [rerank] → select → generate → [verify] → END.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from sentio_tpu.config import Settings, get_settings
from sentio_tpu.graph.executor import END, CompiledGraph, GraphBuilder
from sentio_tpu.graph.nodes import (
    confidence_gate_router,
    create_confidence_gate_node,
    create_document_selector_node,
    create_generator_node,
    create_reranker_node,
    create_retriever_node,
    create_verifier_node,
)

VERIFY_MODES = ("sync", "async", "gated")


@dataclass
class GraphConfig:
    use_reranker: bool = True
    use_verifier: bool = True
    # sync  — verify blocks the response (reference behavior);
    # async — verify runs as a DETACHED trailing node (the executor
    #         launches it off-thread and ends the graph immediately;
    #         verdict lands on the flight record);
    # gated — a confidence gate (ops/confidence.py) short-circuits verify
    #         entirely for confident answers; the rest go async.
    # None = resolve from settings.generator.verify_mode at build time.
    verify_mode: Optional[str] = None
    settings: Settings = field(default_factory=get_settings)

    @classmethod
    def from_settings(cls, settings: Optional[Settings] = None) -> "GraphConfig":
        settings = settings or get_settings()
        return cls(
            use_reranker=settings.rerank.enabled,
            use_verifier=settings.generator.use_verifier,
            verify_mode=settings.generator.verify_mode,
            settings=settings,
        )


def build_basic_graph(
    retriever,
    generator,
    reranker=None,
    verifier=None,
    config: Optional[GraphConfig] = None,
) -> CompiledGraph:
    config = config or GraphConfig.from_settings()
    settings = config.settings
    builder = GraphBuilder()

    builder.add_node("retrieve", create_retriever_node(retriever, settings))
    use_rerank = config.use_reranker and reranker is not None
    if use_rerank:
        builder.add_node("rerank", create_reranker_node(reranker, settings))
    builder.add_node("select", create_document_selector_node(settings))
    builder.add_node("generate", create_generator_node(generator, settings))
    use_verify = config.use_verifier and verifier is not None
    mode = config.verify_mode or settings.generator.verify_mode or "sync"
    if mode not in VERIFY_MODES:
        raise ValueError(
            f"verify_mode must be one of {VERIFY_MODES}, got {mode!r}"
        )
    if use_verify:
        # async/gated: verify is a DETACHED trailing node — the executor
        # fires it off-thread and the graph (hence the HTTP response)
        # returns at the generate/gate boundary; the verdict lands on the
        # flight record. gated additionally fronts it with the confidence
        # gate, whose conditional edge ends the graph outright for
        # confident answers (no verify admission at all).
        builder.add_node(
            "verify", create_verifier_node(verifier, settings, mode=mode),
            detached=mode in ("async", "gated"),
        )
        if mode == "gated":
            builder.add_node("verify_gate",
                             create_confidence_gate_node(settings))

    builder.set_entry("retrieve")
    builder.add_edge("retrieve", "rerank" if use_rerank else "select")
    if use_rerank:
        builder.add_edge("rerank", "select")
    builder.add_edge("select", "generate")
    if not use_verify:
        builder.add_edge("generate", END)
    elif mode == "gated":
        builder.add_edge("generate", "verify_gate")
        builder.add_conditional_edge("verify_gate", confidence_gate_router)
        builder.add_edge("verify", END)
    else:
        builder.add_edge("generate", "verify")
        builder.add_edge("verify", END)
    return builder.compile()


def build_streaming_graph(*args, **kwargs) -> CompiledGraph:
    """Streaming runs the same pipeline; the serving layer streams the
    generator stage directly (the reference's alias, factory.py:191-208)."""
    return build_basic_graph(*args, **kwargs)
