"""Pipeline state flowing through the RAG graph.

Parity with the reference's ``RAGState`` TypedDict + pure mutators
(/root/reference/src/core/graph/state.py:10-139): query, retrieved/reranked/
selected documents, response, metadata, evaluation. State is a plain dict and
every mutator is pure (returns a new dict) — node functions return *partial*
updates which the executor merges, which is also what makes the executor
trivially resumable and traceable.
"""

from __future__ import annotations

import time
import uuid
from typing import Any, TypedDict

from sentio_tpu.models.document import Document


class RAGState(TypedDict, total=False):
    query: str
    query_id: str
    retrieved_documents: list[Document]
    reranked_documents: list[Document]
    selected_documents: list[Document]
    context: str
    response: str
    metadata: dict[str, Any]
    evaluation: dict[str, Any]


def create_initial_state(query: str, metadata: dict[str, Any] | None = None) -> RAGState:
    return RAGState(
        query=query,
        query_id=str(uuid.uuid4()),
        retrieved_documents=[],
        reranked_documents=[],
        selected_documents=[],
        context="",
        response="",
        metadata=dict(metadata or {}),
        evaluation={},
    )


def _merged_meta(state: RAGState, extra: dict[str, Any]) -> dict[str, Any]:
    meta = dict(state.get("metadata", {}))
    meta.update(extra)
    return meta


def add_retrieved_documents(state: RAGState, docs: list[Document]) -> RAGState:
    new = dict(state)
    new["retrieved_documents"] = list(docs)
    new["metadata"] = _merged_meta(state, {"num_retrieved": len(docs), "retrieved_at": time.time()})  # wall-clock: reported metadata timestamp
    return new  # type: ignore[return-value]


def add_reranked_documents(state: RAGState, docs: list[Document]) -> RAGState:
    new = dict(state)
    new["reranked_documents"] = list(docs)
    new["metadata"] = _merged_meta(state, {"num_reranked": len(docs)})
    return new  # type: ignore[return-value]


def add_selected_documents(state: RAGState, docs: list[Document], context: str = "") -> RAGState:
    new = dict(state)
    new["selected_documents"] = list(docs)
    if context:
        new["context"] = context
    new["metadata"] = _merged_meta(state, {"num_selected": len(docs)})
    return new  # type: ignore[return-value]


def set_response(state: RAGState, response: str, **meta: Any) -> RAGState:
    new = dict(state)
    new["response"] = response
    if meta:
        new["metadata"] = _merged_meta(state, meta)
    return new  # type: ignore[return-value]


def set_evaluation(state: RAGState, evaluation: dict[str, Any]) -> RAGState:
    new = dict(state)
    new["evaluation"] = dict(evaluation)
    return new  # type: ignore[return-value]


def deadline_ts(state: RAGState) -> float | None:
    """The request's absolute ``time.perf_counter()`` deadline, if the
    serving layer stamped one into metadata (``deadline_ts``). Nodes use it
    to bound decode work and to skip optional stages for expired callers."""
    value = state.get("metadata", {}).get("deadline_ts")
    try:
        return float(value) if value is not None else None
    except (TypeError, ValueError):
        return None


def deadline_remaining_s(state: RAGState) -> float | None:
    """Seconds left on the request deadline (negative = expired); None when
    the request carries no deadline."""
    ts = deadline_ts(state)
    if ts is None:
        return None
    return ts - time.perf_counter()


def best_documents(state: RAGState) -> list[Document]:
    """The most-processed document list available — selector falls back through
    reranked → retrieved (reference nodes.py:269-301 semantics)."""
    for key in ("selected_documents", "reranked_documents", "retrieved_documents"):
        docs = state.get(key)
        if docs:
            return docs  # type: ignore[return-value]
    return []
