"""Graph node factories: retrieve → rerank → select → generate → verify.

Parity with /root/reference/src/core/graph/nodes.py:37-478: per-request
``user_top_k`` override, content-normalization via ``Document.content``,
the selector's sort/dedup/token-budget pass (≈4 chars/token heuristic,
nodes.py:276-338 there), the generator's mode/temperature metadata, and the
verifier rewriting the answer on a ``fail`` verdict (:471-472). Every node
returns a *partial* state update and records soft errors in metadata instead
of raising — the executor's soft-fail plus these per-node catches reproduce
the reference's "every stage degrades, nothing 500s" ladder.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Any, Optional

from sentio_tpu.config import Settings, get_settings
from sentio_tpu.graph.state import (
    RAGState,
    best_documents,
    deadline_remaining_s,
    deadline_ts,
)
from sentio_tpu.models.document import Document

logger = logging.getLogger(__name__)


def _user_top_k(state: RAGState, default: int, cap: int = 50) -> int:
    raw = state.get("metadata", {}).get("user_top_k")
    if raw is None:
        return default
    try:
        return max(1, min(int(raw), cap))
    except (TypeError, ValueError):
        return default


def create_retriever_node(retriever, settings: Optional[Settings] = None):
    settings = settings or get_settings()

    async def retrieve_node(state: RAGState) -> dict[str, Any]:
        top_k = _user_top_k(state, settings.retrieval.top_k)
        t0 = time.perf_counter()
        try:
            docs = await retriever.aretrieve(state["query"], top_k=top_k)
        except Exception as exc:  # noqa: BLE001
            logger.exception("retrieval failed")
            return {"retrieved_documents": [], "metadata": {"retrieval_error": str(exc)}}
        return {
            "retrieved_documents": docs,
            "metadata": {
                "num_retrieved": len(docs),
                "retrieval_ms": round((time.perf_counter() - t0) * 1000, 2),
                "retriever": getattr(retriever, "name", "unknown"),
            },
        }

    return retrieve_node


def create_reranker_node(reranker, settings: Optional[Settings] = None):
    settings = settings or get_settings()

    async def rerank_node(state: RAGState) -> dict[str, Any]:
        docs = state.get("retrieved_documents") or []
        if not docs:
            return {"reranked_documents": [], "metadata": {"num_reranked": 0}}
        top_k = _user_top_k(state, settings.rerank.top_k)
        t0 = time.perf_counter()
        result = await reranker.arerank(state["query"], docs, top_k=top_k)
        return {
            "reranked_documents": result.documents,
            "metadata": {
                "num_reranked": len(result.documents),
                "rerank_ms": round((time.perf_counter() - t0) * 1000, 2),
                "reranker": result.model,
                "rerank_fallback": result.fallback_used,
            },
        }

    return rerank_node


CHARS_PER_TOKEN = 4  # the selector's ≈4-chars/token budget heuristic


def select_documents(
    docs: list, budget_tokens: int
) -> tuple[list[Document], int]:
    """Sort by best score, dedup by id, enforce the ≈4-chars/token context
    budget (reference nodes.py:276-338). Shared by the graph's select node
    and the SSE streaming path so the two can never drift."""
    docs = sorted(docs, key=lambda d: d.score(), reverse=True)
    seen: set[str] = set()
    budget_chars = budget_tokens * CHARS_PER_TOKEN
    used = 0
    selected: list[Document] = []
    for doc in docs:
        if doc.id in seen:
            continue
        seen.add(doc.id)
        text = doc.content
        if not text.strip():
            continue
        cost = len(text)
        if used + cost > budget_chars and selected:
            continue  # keep scanning: a shorter doc may still fit
        selected.append(doc)
        used += cost
        if used >= budget_chars:
            break
    return selected, used


def create_document_selector_node(settings: Optional[Settings] = None):
    settings = settings or get_settings()
    budget_tokens = settings.generator.context_token_budget

    def select_node(state: RAGState) -> dict[str, Any]:
        docs = state.get("reranked_documents") or state.get("retrieved_documents") or []
        selected, used = select_documents(docs, budget_tokens)
        return {
            "selected_documents": selected,
            "metadata": {
                "num_selected": len(selected),
                "context_chars": used,
                "context_budget_chars": budget_tokens * CHARS_PER_TOKEN,
            },
        }

    return select_node


def create_generator_node(generator, settings: Optional[Settings] = None):
    settings = settings or get_settings()

    async def generate_node(state: RAGState) -> dict[str, Any]:
        docs = best_documents(state)
        meta = state.get("metadata", {})
        mode = meta.get("mode") or settings.generator.mode
        temperature = meta.get("temperature")
        # flight-recorder trace context: ties this generation's engine
        # tickets/ticks to the serving layer's request id
        request_id = meta.get("query_id")
        # caller deadline: rides metadata from the HTTP layer down into the
        # decode service's ticket, so an expired caller's decode is cancelled
        deadline = deadline_ts(state)
        # WFQ tenant key + priority tier (multi-replica tier): the decode
        # admission is charged against this tenant's fair-share quota
        tenant = meta.get("tenant")
        priority = meta.get("priority")
        # logprob accumulators from the paged decode (runtime/paged.py):
        # filled in place by the provider when the serving path carries
        # them; the confidence gate scores them after this node
        gen_stats: dict[str, Any] = {}
        t0 = time.perf_counter()
        try:
            # device generation is the longest stage — keep it off the event
            # loop so concurrent requests, streams, and health checks proceed
            answer = await asyncio.get_running_loop().run_in_executor(
                None,
                lambda: generator.generate(
                    state["query"], docs, mode=mode,
                    temperature=temperature if temperature is None else float(temperature),
                    request_id=str(request_id) if request_id else None,
                    deadline_ts=deadline,
                    tenant=str(tenant) if tenant else None,
                    priority=str(priority) if priority else None,
                    stats=gen_stats,
                ),
            )
        except Exception as exc:  # noqa: BLE001
            if getattr(exc, "soft_fail_exempt", False):
                raise  # shed/deadline errors surface as 429/503/504, not prose
            logger.exception("generation failed")
            return {"response": "", "metadata": {"generation_error": str(exc)}}
        update_meta: dict[str, Any] = {
            "generation_ms": round((time.perf_counter() - t0) * 1000, 2),
            "generation_mode": mode,
            "generator": getattr(generator.provider, "name", "unknown"),
        }
        if gen_stats.get("logprob_count"):
            update_meta["logprob_mean"] = round(gen_stats["logprob_mean"], 4)
            update_meta["logprob_min"] = round(gen_stats["logprob_min"], 4)
            update_meta["logprob_count"] = gen_stats["logprob_count"]
        if gen_stats.get("replica_id") is not None:
            # which serving replica decoded the answer: downstream node
            # spans (verify) and traces carry it as a correlation key
            update_meta["replica_id"] = gen_stats["replica_id"]
        return {"response": answer, "metadata": update_meta}

    return generate_node


def _record_verify(request_id: Optional[str], mode: str, outcome: str,
                   confidence: Optional[float] = None,
                   verdict_ms: Optional[float] = None,
                   skipped: Optional[str] = None) -> None:
    """One per-request verify record, published to BOTH evidence surfaces:
    the ``sentio_tpu_verify_total{mode,outcome}`` counter + confidence
    histogram in /metrics, and the request's flight record (``verify``
    section — what ``sentio trace`` and ``/debug/flight/{id}`` print).
    Best-effort: telemetry must never fail a verdict."""
    try:
        from sentio_tpu.infra.flight import get_flight_recorder
        from sentio_tpu.infra.metrics import get_metrics

        get_metrics().record_verify(mode, outcome, confidence=confidence)
        if request_id:
            fields: dict[str, Any] = {"mode": mode, "outcome": outcome}
            if confidence is not None:
                fields["confidence"] = round(float(confidence), 4)
            if verdict_ms is not None:
                fields["verdict_ms"] = round(float(verdict_ms), 2)
            if skipped is not None:
                fields["skipped"] = skipped
            get_flight_recorder().note_verify(str(request_id), **fields)
    except Exception:  # noqa: BLE001
        logger.debug("verify telemetry failed", exc_info=True)


def confidence_skip_evaluation(confidence: float) -> dict[str, Any]:
    """THE typed ``skipped_confident`` verdict shape — shared by the graph
    gate node and the SSE streaming handler so the two surfaces can never
    drift."""
    return {
        "verdict": "skipped_confident",
        "citations_ok": True,
        "confidence": round(float(confidence), 4),
        "notes": [],
    }


def create_confidence_gate_node(settings: Optional[Settings] = None):
    """The ``verify_gate`` node (VERIFY_MODE=gated): scores the generation's
    logprob accumulators + retrieval fusion margins (ops/confidence.py) and,
    at or above ``verify_confidence_threshold``, short-circuits verification
    with a typed ``skipped_confident`` verdict — zero verify-decode
    admissions, the whole audit round-trip saved. Below threshold (or with
    no logprob signal at all) it stamps the score and routes on to the
    detached verify node."""
    settings = settings or get_settings()
    threshold = settings.generator.verify_confidence_threshold

    def gate_node(state: RAGState) -> dict[str, Any]:
        from sentio_tpu.ops.confidence import confidence_score

        meta = state.get("metadata", {})
        request_id = meta.get("query_id")
        answer = state.get("response", "")
        if not answer:
            # nothing to audit; the verify node's empty-answer warn applies
            return {"metadata": {"verify_confidence": None}}
        conf = confidence_score(
            meta.get("logprob_mean"), meta.get("logprob_min"),
            best_documents(state),
        )
        if conf is not None and conf >= threshold:
            _record_verify(request_id, "gated", "skipped_confident",
                           confidence=conf, skipped="confident")
            return {
                "evaluation": confidence_skip_evaluation(conf),
                "metadata": {
                    "verify_confidence": round(conf, 4),
                    "verify_skipped": "confident",
                },
            }
        return {"metadata": {
            "verify_confidence": None if conf is None else round(conf, 4),
        }}

    return gate_node


def confidence_gate_router(state: RAGState) -> str:
    """Conditional edge after ``verify_gate``: confident answers end the
    graph (no verify at all); everything else proceeds to ``verify``."""
    from sentio_tpu.graph.executor import END

    if state.get("metadata", {}).get("verify_skipped") == "confident":
        return END
    return "verify"


def create_verifier_node(verifier, settings: Optional[Settings] = None,
                         mode: str = "sync"):
    settings = settings or get_settings()

    async def verify_node(state: RAGState) -> dict[str, Any]:
        answer = state.get("response", "")
        if not answer:
            # recorded like every other terminal outcome: in async/gated
            # mode the caller holds verify_pending and polls the flight
            # record — an unrecorded return would leave it pending forever
            _record_verify(state.get("metadata", {}).get("query_id"),
                           mode, "skipped_empty", skipped="empty")
            return {"evaluation": {"verdict": "warn", "notes": ["empty answer"]}}
        # verification is an optional quality stage: with the caller's
        # deadline already spent, running it would burn decode ticks on an
        # answer nobody may read in time — return the unverified answer
        remaining = deadline_remaining_s(state)
        if remaining is not None and remaining <= 0:
            _record_verify(state.get("metadata", {}).get("query_id"),
                           mode, "skipped_deadline", skipped="deadline")
            return {
                "evaluation": {
                    "verdict": "skip",
                    "notes": ["deadline expired; verification skipped"],
                },
                "metadata": {"verify_skipped": "deadline"},
            }
        docs = best_documents(state)
        # same trace id as the generate node: the verify admission lands on
        # the same flight record, where its prefix_hit_tokens show the
        # generate prompt head being reused from the radix cache
        meta = state.get("metadata", {})
        request_id = meta.get("query_id")
        # the remaining deadline bounds the audit decode too — without it
        # the pump's expiry sweep could never cancel an expired caller's
        # verify slot (verifier soft-fails internally, so an expiry here
        # degrades to a 'warn' verdict rather than failing the answer)
        deadline = deadline_ts(state)
        # WFQ tenant + priority: the verify admission is charged to the
        # REQUESTING tenant, exactly like the generate admission — verify
        # traffic riding the shared tenant would let one tenant's verify
        # load starve every other tenant's quota for free
        tenant = meta.get("tenant")
        priority = meta.get("priority")
        t0 = time.perf_counter()
        result = await asyncio.get_running_loop().run_in_executor(
            None,
            lambda: verifier.verify(
                state["query"], answer, docs,
                request_id=str(request_id) if request_id else None,
                deadline_ts=deadline,
                tenant=str(tenant) if tenant else None,
                priority=str(priority) if priority else None,
            ),
        )
        verdict_ms = round((time.perf_counter() - t0) * 1000, 2)
        _record_verify(
            str(request_id) if request_id else None, mode, result.verdict,
            confidence=meta.get("verify_confidence"), verdict_ms=verdict_ms,
        )
        update: dict[str, Any] = {
            "evaluation": result.to_dict(),
            "metadata": {
                "verify_ms": verdict_ms,
                "verdict": result.verdict,
            },
        }
        if result.verdict == "fail" and result.revised_answer:
            # sync mode only in practice: a detached verify's update is
            # discarded by the executor — the answer already shipped, so a
            # late rewrite has nowhere to go (the verdict still lands on
            # the flight record for the caller to fetch)
            update["response"] = result.revised_answer
            update["metadata"]["answer_revised"] = True
        return update

    return verify_node
