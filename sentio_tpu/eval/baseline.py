"""Measured reference-architecture baseline (SURVEY.md §6: "we must measure
the baseline ourselves").

The reference stack itself cannot run here (its deps — langgraph, FastAPI,
rank_bm25, qdrant-client — are not in the image, and its model calls need
remote APIs this zero-egress environment cannot reach). What CAN be
measured faithfully is its *architecture*: the same pipeline shape
(/root/reference/src/core/graph/factory.py:94-188 — retrieve(dense+sparse
fused) → rerank → select → generate → verify) with every ML step behind a
REAL HTTP process boundary, exactly where the reference calls Jina/OpenAI
(jina.py:33, jina_reranker.py:120, openai.py:117 there), served by a
loopback mock-model server using the reference's own test fakes (hash
embeddings, jina_reranker.py:297's decaying default ranking, canned chat).

This is a deliberate LOWER bound for the reference: zero network latency,
zero model compute. Every millisecond it records is pure architecture cost
— HTTP framing, JSON serialization of document payloads, python-loop
retrieval math (rank_bm25-style scoring, per-doc cosine, O(k²) MMR) — the
cost our in-process device-dispatch design removes. Real deployments add
10–400 ms of WAN latency per hop on top; SENTIO_BASELINE_RTT_MS injects a
per-hop delay for sensitivity studies but defaults to 0 so the recorded
baseline is never fabricated.
"""

from __future__ import annotations

import asyncio
import json
import math
import re
import threading
import time
from collections import defaultdict
from typing import Optional, Sequence

import numpy as np

from sentio_tpu.models.document import Document

RRF_K = 20  # the reference's tuned value (retrievers/factory.py:29-34 there)


# ------------------------------------------------------- loopback mock APIs


class MockModelServer:
    """aiohttp server with the reference's three remote-model surfaces,
    implemented with its own mock-mode semantics (deterministic hash
    embeddings, identity rerank with decaying scores, canned chat)."""

    def __init__(self, dim: int = 1024, rtt_ms: float = 0.0) -> None:
        self.dim = dim
        self.rtt_s = max(rtt_ms, 0.0) / 1000.0
        self.port: Optional[int] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None
        self._started = threading.Event()
        self.calls = defaultdict(int)

    # hash-embedding identical to the reference's empty-key mock mode
    # (jina.py:141-159 there): deterministic per-text pseudo-vectors
    def _embed(self, texts: list[str]) -> np.ndarray:
        from sentio_tpu.ops.embedder import HashEmbedder

        if not hasattr(self, "_hash"):
            from sentio_tpu.config import EmbedderConfig

            self._hash = HashEmbedder(EmbedderConfig(provider="hash", dim=self.dim, cache_size=0))
        return self._hash._embed_batch(texts)

    async def _maybe_delay(self) -> None:
        if self.rtt_s:
            await asyncio.sleep(self.rtt_s)

    async def _h_embed(self, request):
        from aiohttp import web

        await self._maybe_delay()
        body = await request.json()
        self.calls["embeddings"] += 1
        vecs = self._embed(body["input"])
        return web.json_response(
            {"data": [{"embedding": v.tolist(), "index": i} for i, v in enumerate(vecs)]}
        )

    async def _h_rerank(self, request):
        from aiohttp import web

        await self._maybe_delay()
        body = await request.json()
        self.calls["rerank"] += 1
        n = len(body["documents"])
        # the reference's fallback/default ranking: original order with
        # scores 1.0 - 0.1*idx (jina_reranker.py:297-322 there)
        results = [
            {"index": i, "relevance_score": max(1.0 - 0.1 * i, 0.0)} for i in range(n)
        ]
        return web.json_response({"results": results[: body.get("top_n", n)]})

    async def _h_chat(self, request):
        from aiohttp import web

        await self._maybe_delay()
        body = await request.json()
        self.calls["chat"] += 1
        content = body["messages"][-1]["content"]
        if '"verdict"' in content or "citations_ok" in content:
            reply = '{"verdict": "pass", "citations_ok": true, "notes": []}'
        else:
            first = content.splitlines()[0][:120] if content else ""
            reply = f"Based on the provided sources: {first}"
        return web.json_response({"choices": [{"message": {"content": reply}}]})

    def start(self) -> "MockModelServer":
        from aiohttp import web

        app = web.Application()
        app.router.add_post("/v1/embeddings", self._h_embed)
        app.router.add_post("/v1/rerank", self._h_rerank)
        app.router.add_post("/v1/chat/completions", self._h_chat)

        def run() -> None:
            loop = asyncio.new_event_loop()
            asyncio.set_event_loop(loop)
            # baselined cross-thread-race (here and .port below): written
            # once by the server thread BEFORE _started.set(); the caller
            # only reads after _started.wait() — the Event is the
            # happens-before edge, no lock needed
            self._loop = loop
            runner = web.AppRunner(app)
            loop.run_until_complete(runner.setup())
            site = web.TCPSite(runner, "127.0.0.1", 0)
            loop.run_until_complete(site.start())
            self.port = site._server.sockets[0].getsockname()[1]
            self._started.set()
            loop.run_forever()
            loop.run_until_complete(runner.cleanup())

        self._thread = threading.Thread(target=run, daemon=True, name="mock-model-api")
        self._thread.start()
        if not self._started.wait(10.0):
            raise RuntimeError("mock model server failed to start")
        return self

    def stop(self) -> None:
        if self._loop is not None:
            self._loop.call_soon_threadsafe(self._loop.stop)
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    @property
    def base_url(self) -> str:
        return f"http://127.0.0.1:{self.port}"


# ---------------------------------------------- reference-shape host pipeline


class _PyBM25:
    """rank_bm25-style scorer: python dict walk per document per query —
    the reference's sparse leg (sparse.py:33-203 there, `rank_bm25` Okapi)."""

    def __init__(self, docs: Sequence[Document], k1: float = 1.5, b: float = 0.75) -> None:
        self.k1, self.b = k1, b
        self.docs = list(docs)
        self.doc_tfs: list[dict[str, int]] = []
        df: dict[str, int] = defaultdict(int)
        lens = []
        for doc in docs:
            toks = doc.content.lower().split()
            tf: dict[str, int] = defaultdict(int)
            for t in toks:
                tf[t] += 1
            self.doc_tfs.append(dict(tf))
            lens.append(len(toks))
            for t in tf:
                df[t] += 1
        n = max(len(self.docs), 1)
        self.avgdl = sum(lens) / n if lens else 0.0
        self.doc_lens = lens
        self.idf = {
            t: math.log(1.0 + (n - d + 0.5) / (d + 0.5)) for t, d in df.items()
        }

    def top_k(self, query: str, k: int) -> list[tuple[int, float]]:
        q_toks = query.lower().split()
        scores = []
        for di, tf in enumerate(self.doc_tfs):  # the hot python loop
            s = 0.0
            norm = self.k1 * (1 - self.b + self.b * self.doc_lens[di] / max(self.avgdl, 1e-9))
            for t in q_toks:
                f = tf.get(t)
                if f:
                    s += self.idf.get(t, 0.0) * f * (self.k1 + 1) / (f + norm)
            if s > 0:
                scores.append((di, s))
        scores.sort(key=lambda x: -x[1])
        return scores[:k]


class ReferenceShapePipeline:
    """The reference's /chat hot path (SURVEY.md §3.1), process boundaries
    included: embed-query HTTP → dense cosine → python BM25 → RRF dict merge
    → scorer plugins (keyword regex + semantic re-embed via HTTP + MMR loop)
    → rerank HTTP → token-budget select → generate HTTP → verify HTTP."""

    def __init__(
        self,
        server: MockModelServer,
        documents: Sequence[Document],
        top_k: int = 10,
        use_rerank: bool = True,
        use_verify: bool = True,
        use_scorers: bool = True,
    ) -> None:
        import httpx

        self.server = server
        self.docs = list(documents)
        self.top_k = top_k
        self.use_rerank = use_rerank
        self.use_verify = use_verify
        self.use_scorers = use_scorers
        self.client = httpx.Client(base_url=server.base_url, timeout=30.0)
        # corpus ingestion exactly like the reference: batched embed calls
        # of <= 100 texts over HTTP (jina.py:229-236 there)
        vecs = []
        texts = [d.content for d in self.docs]
        for start in range(0, len(texts), 100):
            vecs.append(self._embed_http(texts[start : start + 100]))
        self.matrix = np.concatenate(vecs, axis=0) if vecs else np.zeros((0, server.dim))
        self.matrix /= np.maximum(np.linalg.norm(self.matrix, axis=1, keepdims=True), 1e-9)
        self.bm25 = _PyBM25(self.docs)

    def close(self) -> None:
        self.client.close()

    # ------------------------------------------------------------ HTTP hops

    def _embed_http(self, texts: list[str]) -> np.ndarray:
        resp = self.client.post("/v1/embeddings", json={"input": texts})
        resp.raise_for_status()
        data = resp.json()["data"]
        return np.asarray([d["embedding"] for d in data], np.float32)

    def _rerank_http(self, query: str, docs: list[Document], top_n: int) -> list[Document]:
        resp = self.client.post(
            "/v1/rerank",
            json={"query": query, "documents": [d.content for d in docs], "top_n": top_n},
        )
        resp.raise_for_status()
        order = resp.json()["results"]
        return [docs[r["index"]] for r in order]

    def _chat_http(self, prompt: str) -> str:
        resp = self.client.post(
            "/v1/chat/completions",
            json={"messages": [{"role": "user", "content": prompt}], "max_tokens": 1024},
        )
        resp.raise_for_status()
        return resp.json()["choices"][0]["message"]["content"]

    # -------------------------------------------------------------- retrieval

    def retrieve(self, query: str) -> list[Document]:
        pool = self.top_k * 2
        q_vec = self._embed_http([query])[0]
        q_vec /= max(np.linalg.norm(q_vec), 1e-9)
        sims = self.matrix @ q_vec
        dense_idx = np.argsort(-sims)[:pool]
        sparse_hits = self.bm25.top_k(query, pool)

        # RRF dict merge (hybrid.py:204-259 there)
        fused: dict[int, float] = defaultdict(float)
        for rank, di in enumerate(dense_idx):
            fused[int(di)] += 1.0 / (RRF_K + rank)
        for rank, (di, _s) in enumerate(sparse_hits):
            fused[di] += 1.0 / (RRF_K + rank)

        merged = [self.docs[di] for di in fused]
        if self.use_scorers and merged:
            # keyword overlap scorer (scorers.py:25-72 there)
            q_words = set(re.findall(r"\w+", query.lower()))
            for di in list(fused):
                words = set(re.findall(r"\w+", self.docs[di].content.lower()))
                overlap = len(q_words & words) / max(len(q_words), 1)
                fused[di] += 0.8 * overlap
            # semantic scorer: re-embeds the candidate docs over HTTP per
            # query — the N+1 the reference pays (scorers.py:131-191 there)
            texts = [d.content for d in merged]
            doc_vecs = self._embed_http(texts)
            for (di, _), vec in zip(fused.items(), doc_vecs):
                denom = max(np.linalg.norm(vec) * np.linalg.norm(q_vec), 1e-9)
                fused[di] += 0.5 * float(np.dot(vec, q_vec) / denom)
            # MMR diversification: greedy O(k²) python loop (scorers.py:194+)
            chosen: list[int] = []
            cand = list(fused)
            while cand and len(chosen) < self.top_k:
                best, best_score = None, -1e9
                for di in cand:
                    rel = fused[di]
                    red = 0.0
                    for cj in chosen:
                        a, b = doc_vecs[merged.index(self.docs[di])], doc_vecs[merged.index(self.docs[cj])]
                        red = max(red, float(np.dot(a, b) / max(np.linalg.norm(a) * np.linalg.norm(b), 1e-9)))
                    score = 0.7 * rel - 0.3 * red
                    if score > best_score:
                        best, best_score = di, score
                chosen.append(best)
                cand.remove(best)
            ranked = chosen
        else:
            ranked = [di for di, _ in sorted(fused.items(), key=lambda x: -x[1])[: self.top_k]]
        return [self.docs[di] for di in ranked[: self.top_k]]

    # ------------------------------------------------------------------ chat

    def chat(self, question: str) -> tuple[list[Document], str]:
        docs = self.retrieve(question)
        if self.use_rerank and docs:
            docs = self._rerank_http(question, docs, self.top_k)
        # token-budget select: ~4 chars/token, 2000-token cap (nodes.py:296-338)
        budget_chars = 2000 * 4
        selected, used = [], 0
        for doc in docs:
            if used + len(doc.content) > budget_chars and selected:
                break
            selected.append(doc)
            used += len(doc.content)
        context = "\n\n".join(
            f"[{i}] Source: {d.metadata.get('source', d.id)}\n{d.content}"
            for i, d in enumerate(selected, 1)
        )
        answer = self._chat_http(f"{context}\n\nQuestion: {question}\nAnswer:")
        if self.use_verify:
            self._chat_http(
                f'Audit this answer. Reply JSON {{"verdict": ..., "citations_ok": ...}}\n'
                f"Answer: {answer}\nContext: {context[:2000]}"
            )
        return selected, answer


def measure_baseline(
    documents: Sequence[Document],
    queries: Sequence[tuple[str, str]],
    dim: int = 1024,
    rtt_ms: float = 0.0,
    use_scorers: bool = True,
):
    """Stand up the loopback mock APIs, run the reference-shape pipeline
    over the queries, and return (EvalResult, per-query HTTP-call counts)."""
    from sentio_tpu.eval.harness import run_queries

    server = MockModelServer(dim=dim, rtt_ms=rtt_ms).start()
    t0 = time.perf_counter()
    pipeline = ReferenceShapePipeline(server, documents, use_scorers=use_scorers)
    ingest_s = time.perf_counter() - t0
    try:
        result = run_queries("reference-baseline", pipeline.chat, queries)
        result.extras["ingest_s"] = round(ingest_s, 2)
        result.extras["http_calls"] = dict(server.calls)
        return result
    finally:
        pipeline.close()
        server.stop()


def _self_check() -> None:  # pragma: no cover — manual smoke
    from sentio_tpu.eval.dataset import build_bundle

    bundle = build_bundle(n_docs=128, n_queries=8)
    result = measure_baseline(bundle.documents, bundle.queries, dim=256)
    print(json.dumps(result.row(), indent=1))


if __name__ == "__main__":  # pragma: no cover
    _self_check()
