"""Run pipeline configurations over an EvalBundle: recall@10, p50, QPS.

A pipeline under test is just ``fn(question) -> (documents, answer)``; the
harness times it (optionally with concurrent callers, which is how the
batched-serving config is exercised — concurrency IS the batch on this
stack) and scores retrieval against the gold ids.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Sequence

PipelineFn = Callable[[str], tuple[Sequence, str]]


@dataclass
class EvalResult:
    name: str
    n_queries: int
    recall_at_10: float
    p50_ms: float
    p95_ms: float
    qps: float
    errors: int = 0
    extras: dict = field(default_factory=dict)

    def row(self) -> dict:
        return {
            "config": self.name,
            "recall@10": round(self.recall_at_10, 3),
            "p50_ms": round(self.p50_ms, 1),
            "p95_ms": round(self.p95_ms, 1),
            "qps": round(self.qps, 2),
            "n": self.n_queries,
            **({"errors": self.errors} if self.errors else {}),
            **self.extras,
        }


def recall_at_k(retrieved_ids: Sequence[str], gold_id: str, k: int = 10) -> float:
    return 1.0 if gold_id in list(retrieved_ids)[:k] else 0.0


def _percentile(sorted_vals: list[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(int(len(sorted_vals) * q), len(sorted_vals) - 1)
    return sorted_vals[idx]


def run_queries(
    name: str,
    fn: PipelineFn,
    queries: Sequence[tuple[str, str]],
    concurrent: int = 1,
    warmup: int = 1,
) -> EvalResult:
    """Execute every (question, gold_id) through ``fn``.

    ``concurrent`` > 1 drives the queries from that many worker threads —
    wall-clock QPS then reflects batched/coalesced serving, while per-query
    latency still measures each caller's own wait.
    """
    for i in range(min(warmup, len(queries))):
        fn(queries[i][0])

    latencies: list[float] = []
    hits: list[float] = []
    errors = 0
    lock = threading.Lock()

    def one(question: str, gold_id: str) -> None:
        nonlocal errors
        t0 = time.perf_counter()
        try:
            docs, _answer = fn(question)
            dt_ms = (time.perf_counter() - t0) * 1000.0
            ids = [getattr(d, "id", d) for d in docs]
            with lock:
                latencies.append(dt_ms)
                hits.append(recall_at_k(ids, gold_id, 10))
        except Exception:
            with lock:
                errors += 1

    t_start = time.perf_counter()
    if concurrent <= 1:
        for question, gold_id in queries:
            one(question, gold_id)
    else:
        pending = list(queries)
        idx_lock = threading.Lock()

        def worker() -> None:
            while True:
                with idx_lock:
                    if not pending:
                        return
                    question, gold_id = pending.pop(0)
                one(question, gold_id)

        threads = [threading.Thread(target=worker, name=f"eval-worker-{i}")
                   for i in range(concurrent)]
        for t in threads:
            t.start()
        for t in threads:
            # bounded: a worker wedged inside a hung provider call must not
            # hang the whole eval run past the per-query budget
            t.join(timeout=600.0)
        stragglers = sum(1 for t in threads if t.is_alive())
        if stragglers:
            # surfaced, not silent: the result below aggregates a PARTIAL
            # run (the snapshot under the lock keeps the straggler's late
            # appends from racing the sort)
            logging.getLogger(__name__).warning(
                "%d eval worker(s) still wedged after the 600s join; "
                "aggregating partial results", stragglers,
            )
    wall_s = time.perf_counter() - t_start

    with lock:
        latencies = sorted(latencies)
        hits = list(hits)
        n_errors = errors
    n_ok = len(latencies)
    return EvalResult(
        name=name,
        n_queries=len(queries),
        recall_at_10=(sum(hits) / len(hits)) if hits else 0.0,
        p50_ms=_percentile(latencies, 0.50),
        p95_ms=_percentile(latencies, 0.95),
        qps=n_ok / wall_s if wall_s > 0 else 0.0,
        errors=n_errors,
    )
