"""In-tree contrastive training for the bi-encoder — dense retrieval that
actually *works*, with zero egress.

The reference buys retrieval quality from a remote embedding API
(/root/reference/src/core/embeddings/jina.py:30-373 — every embed is an HTTP
call to a pretrained service). A TPU-native framework embeds locally, so
quality must come from weights. There are no pretrained weights in this
image, but the synthetic eval bundle (eval/dataset.py) defines the retrieval
task precisely — so the framework trains its own encoder on bundle-shaped
data and ships the checkpoint through the standard
``save_pytree``/``load_model`` path (runtime/checkpoint.py).

Objective: symmetric InfoNCE with in-batch negatives — the standard
bi-encoder recipe (DPR/SimCSE family). Query and document towers share the
one encoder; embeddings are mean-pooled and L2-normalized, so scoring
matches the serving path (TpuEmbedder → TpuDenseIndex inner product)
bit-for-bit in architecture.

TPU mapping: every step is one jitted ``value_and_grad`` over [B, L] int32
batches — two encoder forwards (queries, docs) + a [B, B] logit matrix, all
MXU matmuls in bf16 params with f32 loss math. Static shapes: queries pad
to ``q_len``, docs to ``d_len``; one compiled program per run.

Train/eval split: training draws from DIFFERENT bundle seeds than the eval
harness (seed 0), so the entity→fact assignments, numeric values, and
phrasing pairings all differ — the encoder must learn the *task* (match
subject/component mentions across paraphrase templates), not memorize the
eval corpus.
"""

from __future__ import annotations

import logging
import time
from dataclasses import asdict, dataclass
from typing import Optional

import numpy as np

from sentio_tpu.eval.dataset import build_bundle
from sentio_tpu.models.transformer import EncoderConfig

logger = logging.getLogger(__name__)


@dataclass(frozen=True)
class TrainConfig:
    steps: int = 600
    batch: int = 64
    lr: float = 3e-4
    tau: float = 0.05          # InfoNCE temperature
    q_len: int = 64            # queries are short questions
    d_len: int = 160           # facts are ~110-130 chars (byte tokenizer)
    n_docs: int = 1024         # per training bundle
    n_queries: int = 4096      # per training bundle
    seeds: tuple = (7, 11, 13)  # training bundles; eval uses seed 0
    warmup: int = 50


def _pairs_from_bundles(cfg: TrainConfig) -> tuple[list[str], list[str]]:
    """(query, gold-document-text) pairs pooled over the training bundles."""
    queries: list[str] = []
    docs: list[str] = []
    for seed in cfg.seeds:
        bundle = build_bundle(n_docs=cfg.n_docs, n_queries=cfg.n_queries, seed=seed)
        by_id = {d.id: d.text for d in bundle.documents}
        for question, gold_id in bundle.queries:
            queries.append(question)
            docs.append(by_id[gold_id])
    return queries, docs


def _tokenize(texts: list[str], tokenizer, max_len: int) -> np.ndarray:
    out = np.full((len(texts), max_len), tokenizer.pad_id, np.int32)
    for i, t in enumerate(texts):
        ids = tokenizer.encode(t, add_bos=True)[:max_len]
        out[i, : len(ids)] = ids
    return out


def train_encoder(
    enc_cfg: Optional[EncoderConfig] = None,
    train_cfg: Optional[TrainConfig] = None,
    out_path: str = "",
    seed: int = 0,
    log_every: int = 50,
) -> tuple[dict, EncoderConfig, dict]:
    """Train the bi-encoder; returns (params, enc_cfg, history). When
    ``out_path`` is set, saves a ``load_model``-compatible checkpoint
    (family=encoder) that ``EMBEDDER_CHECKPOINT`` / ``cli eval
    --encoder-checkpoint`` can restore."""
    import jax
    import jax.numpy as jnp
    import optax

    from sentio_tpu.models.tokenizer import ByteTokenizer
    from sentio_tpu.models.transformer import (
        encoder_forward,
        init_encoder,
        mean_pool,
    )

    enc_cfg = enc_cfg or EncoderConfig(
        vocab_size=512, dim=256, n_layers=4, n_heads=4, mlp_dim=1024, max_len=512
    )
    tc = train_cfg or TrainConfig()
    tokenizer = ByteTokenizer(enc_cfg.vocab_size)

    q_texts, d_texts = _pairs_from_bundles(tc)
    q_ids = _tokenize(q_texts, tokenizer, tc.q_len)
    d_ids = _tokenize(d_texts, tokenizer, tc.d_len)
    n = len(q_texts)
    logger.info("train_encoder: %d pairs, cfg dim=%d layers=%d", n, enc_cfg.dim,
                enc_cfg.n_layers)

    rng = jax.random.PRNGKey(seed)
    params = init_encoder(rng, enc_cfg)
    schedule = optax.warmup_cosine_decay_schedule(
        0.0, tc.lr, tc.warmup, max(tc.steps, tc.warmup + 1)
    )
    tx = optax.adamw(schedule, weight_decay=0.01)
    opt_state = tx.init(params)

    pad = tokenizer.pad_id

    def embed(p, ids):
        # mean_pool already returns L2-normalized float32 — the exact
        # serving-path embedding (TpuEmbedder._fwd)
        mask = ids != pad
        return mean_pool(encoder_forward(p, enc_cfg, ids, mask), mask)

    def loss_fn(p, qb, db):
        q = embed(p, qb)
        d = embed(p, db)
        logits = (q @ d.T) / tc.tau                    # [B, B]
        labels = jnp.arange(q.shape[0])
        # symmetric: query→doc and doc→query both pull the diagonal up
        l_qd = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
        l_dq = optax.softmax_cross_entropy_with_integer_labels(logits.T, labels)
        return 0.5 * (l_qd.mean() + l_dq.mean())

    @jax.jit
    def step(p, opt, qb, db):
        loss, grads = jax.value_and_grad(loss_fn)(p, qb, db)
        updates, opt = tx.update(grads, opt, p)
        return optax.apply_updates(p, updates), opt, loss

    order = np.random.default_rng(seed).permutation(n)
    history: dict = {"loss": [], "steps": tc.steps, "pairs": n}
    t0 = time.perf_counter()
    for i in range(tc.steps):
        lo = (i * tc.batch) % max(n - tc.batch, 1)
        idx = order[lo : lo + tc.batch]
        params, opt_state, loss = step(params, opt_state, q_ids[idx], d_ids[idx])
        if i % log_every == 0 or i == tc.steps - 1:
            lv = float(loss)
            history["loss"].append((i, round(lv, 4)))
            logger.info("train_encoder step %d/%d loss %.4f", i, tc.steps, lv)
        if (i + 1) % (len(order) // tc.batch or 1) == 0:
            order = np.random.default_rng(seed + i + 1).permutation(n)
    history["wall_s"] = round(time.perf_counter() - t0, 1)

    if out_path:
        from sentio_tpu.runtime.checkpoint import save_pytree

        save_pytree(
            out_path, params,
            meta={
                "family": "encoder",
                "config": asdict(enc_cfg),
                "trained": {
                    "objective": "symmetric-infonce",
                    "pairs": n,
                    "steps": tc.steps,
                    "final_loss": history["loss"][-1][1],
                    "bundle_seeds": list(tc.seeds),
                },
            },
        )
        logger.info("train_encoder: saved checkpoint to %s", out_path)
    return params, enc_cfg, history


def eval_recall(
    params, enc_cfg: EncoderConfig, n_docs: int = 1024, n_queries: int = 64,
    seed: int = 0, top_k: int = 10,
) -> float:
    """recall@k of the trained encoder on the EVAL bundle (seed 0 — never
    trained on), through the same TpuEmbedder/TpuDenseIndex serving path
    the harness measures."""
    from sentio_tpu.config import EmbedderConfig
    from sentio_tpu.ops.dense_index import TpuDenseIndex
    from sentio_tpu.ops.embedder import TpuEmbedder

    bundle = build_bundle(n_docs=n_docs, n_queries=n_queries, seed=seed)
    embedder = TpuEmbedder(
        EmbedderConfig(provider="tpu", batch_size=128),
        params=params, model_config=enc_cfg,
    )
    vecs = embedder.embed_many([d.text for d in bundle.documents])
    index = TpuDenseIndex(dim=enc_cfg.dim)
    index.add(bundle.documents, vecs)
    hits = 0
    for question, gold_id in bundle.queries:
        q = embedder.embed(question)
        got = [d.id for d, _ in index.search(np.asarray(q).reshape(-1), top_k)]
        hits += gold_id in got
    return hits / len(bundle.queries)
