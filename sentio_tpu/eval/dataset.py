"""Deterministic synthetic retrieval-QA bundle (NQ-open stand-in).

Structure mirrors what an open-domain QA eval needs: a corpus where many
documents share vocabulary (same categories, same fact templates) but each
fact is uniquely identified by its entity combination, plus natural-language
questions that PARAPHRASE the fact (different wording, partial entity
mention) and carry a gold document id. Retrieval quality is then a real
signal: recall@10 rewards rankers that separate the right entity's fact
from dozens of lexically-similar distractors, and BM25 / dense / hybrid
legs produce *different* scores, not a saturated 100%.

Everything derives from one integer seed — the bundle is reproducible
across processes and platforms (no file assets, zero egress).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from sentio_tpu.models.document import Document

# entity pools — combinations (subject × component) identify a fact
_SUBJECTS = (
    "aurora", "basilisk", "cascade", "dynamo", "ember", "fjord", "granite",
    "harbor", "iris", "juniper", "krait", "lumen", "meridian", "nimbus",
    "onyx", "pinnacle", "quartz", "ridge", "sable", "tundra", "umbra",
    "vortex", "willow", "xenon", "yonder", "zephyr",
)
_COMPONENTS = (
    "compiler", "scheduler", "allocator", "interconnect", "cache", "runtime",
    "decoder", "indexer", "planner", "profiler",
)
_PEOPLE = (
    "ada chen", "grace okafor", "edsger lindqvist", "katherine bose",
    "alan moreau", "hedy nakamura", "radia vance", "barbara ishii",
    "donald petrov", "frances aguilar",
)
_UNITS = ("gigaflops", "queries per second", "megabytes per joule", "tokens per step")

_FACT_TEMPLATES = (
    "The {subject} {component} was designed by {person} in {year}; "
    "it sustains {value} {unit} under production load.",
    "Project {subject} shipped its {component} in {year}. Lead engineer "
    "{person} measured {value} {unit} in the acceptance benchmark.",
    "In {year}, {person} rebuilt the {component} for the {subject} platform, "
    "reaching {value} {unit} on the standard suite.",
)

_QUESTION_TEMPLATES = (
    "who designed the {subject} {component}?",
    "what year did the {subject} {component} ship?",
    "how fast is the {component} of {subject}?",
    "which engineer worked on {subject}'s {component}?",
    "what performance does the {subject} {component} reach?",
)

_NOISE_TEMPLATES = (
    "Meeting notes {i}: the weekly sync covered roadmap priorities, hiring "
    "updates, and the quarterly review schedule for the infrastructure team.",
    "Changelog entry {i}: fixed a flaky integration test, bumped the linter "
    "version, and refreshed the contributor documentation pages.",
    "Incident report {i}: a configuration rollout briefly elevated error "
    "rates; the on-call engineer rolled back and filed a postmortem.",
)


@dataclass
class EvalBundle:
    documents: list  # list[Document]
    queries: list[tuple[str, str]]  # (question, gold document id)
    seed: int

    @property
    def n_facts(self) -> int:
        return sum(1 for d in self.documents if d.id.startswith("fact-"))


def build_bundle(
    n_docs: int = 1024, n_queries: int = 64, seed: int = 0
) -> EvalBundle:
    """Corpus of ``n_docs`` documents (≈70% entity facts, 30% noise) and
    ``n_queries`` paraphrased questions with gold ids."""
    rng = np.random.default_rng(seed)
    combos = [(s, c) for s in _SUBJECTS for c in _COMPONENTS]
    rng.shuffle(combos)
    n_facts = min(max(int(n_docs * 0.7), 1), len(combos))

    documents: list[Document] = []
    for i in range(n_facts):
        subject, component = combos[i]
        person = _PEOPLE[int(rng.integers(len(_PEOPLE)))]
        year = 1990 + int(rng.integers(35))
        value = int(rng.integers(10, 9000))
        unit = _UNITS[int(rng.integers(len(_UNITS)))]
        template = _FACT_TEMPLATES[int(rng.integers(len(_FACT_TEMPLATES)))]
        documents.append(
            Document(
                text=template.format(
                    subject=subject, component=component, person=person,
                    year=year, value=value, unit=unit,
                ),
                id=f"fact-{subject}-{component}",
                metadata={"source": f"{subject}/{component}.md"},
            )
        )
    for i in range(n_docs - n_facts):
        template = _NOISE_TEMPLATES[i % len(_NOISE_TEMPLATES)]
        documents.append(
            Document(
                text=template.format(i=i),
                id=f"noise-{i}",
                metadata={"source": f"notes/{i}.md"},
            )
        )

    queries: list[tuple[str, str]] = []
    for i in range(n_queries):
        subject, component = combos[int(rng.integers(n_facts))]
        template = _QUESTION_TEMPLATES[int(rng.integers(len(_QUESTION_TEMPLATES)))]
        queries.append(
            (template.format(subject=subject, component=component),
             f"fact-{subject}-{component}")
        )
    return EvalBundle(documents=documents, queries=queries, seed=seed)
