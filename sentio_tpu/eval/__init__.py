"""Evaluation harness: bundled retrieval-QA dataset, the five BASELINE.md
pipeline configs, and a measured reference-architecture baseline.

The reference publishes no benchmark numbers (SURVEY.md §6), so parity and
the ≥10× latency target must be measured, not quoted. No public QA dataset
ships in this zero-egress image, so :mod:`dataset` synthesizes a
deterministic NQ-style retrieval-QA bundle (entity-rich facts + paraphrased
questions with gold document labels); :mod:`harness` runs pipeline configs
over it reporting recall@10 / p50 / QPS; :mod:`baseline` measures the
reference's as-shipped architecture — same pipeline shape, mock model
backends behind REAL loopback HTTP hops (its four process boundaries) —
as a conservative lower bound (zero network latency, zero model compute).
"""

from sentio_tpu.eval.dataset import EvalBundle, build_bundle
from sentio_tpu.eval.harness import EvalResult, recall_at_k, run_queries

__all__ = ["EvalBundle", "build_bundle", "EvalResult", "recall_at_k", "run_queries"]
