"""Eval orchestration: the five BASELINE.md configs + the measured baseline.

This is the wiring that turns the eval subsystem into published numbers
(BASELINE.md's measurement matrix, EVAL.json): build the retrieval-QA
bundle, stand up the framework components once, run each config through
:func:`sentio_tpu.eval.harness.run_queries`, and measure the
reference-architecture loopback baseline (:mod:`sentio_tpu.eval.baseline`).

Config map (BASELINE.json → this framework):

1. ``sparse_api``   — BM25-only retrieve + LLM over a REAL loopback HTTP hop
                      (the OpenAI-compatible provider against the mock model
                      server) — the reference's cheapest shape.
2. ``dense``        — on-device bi-encoder embed → in-HBM exact top-k.
3. ``hybrid_rerank``— concurrent dense+sparse legs, RRF fusion, on-device
                      cross-encoder rerank.
4. ``full_paged``   — the whole graph (retrieve → rerank → select → generate
                      → verify) with generation through the continuous-
                      batching paged-KV service; sequential callers.
5. ``batched``      — same graph, N concurrent callers sharing the paged
                      decode batch (concurrency IS the batch).

Run via ``python -m sentio_tpu.cli eval``.
"""

from __future__ import annotations

import sys
import time
from typing import Optional

from sentio_tpu.eval.dataset import EvalBundle, build_bundle
from sentio_tpu.eval.harness import EvalResult, run_queries


def _log(*args) -> None:
    print(*args, file=sys.stderr, flush=True)


def _build_models(scale: str):
    from sentio_tpu.models.llama import LlamaConfig
    from sentio_tpu.models.transformer import EncoderConfig

    if scale == "tiny":
        return EncoderConfig.tiny(), LlamaConfig.tiny()
    # "bench": MXU-friendly mini models (dims multiples of 128, bf16) — the
    # same shapes bench.py serves, so EVAL and BENCH numbers are comparable
    enc = EncoderConfig(
        vocab_size=512, dim=512, n_layers=8, n_heads=8, mlp_dim=2048, max_len=512
    )
    llm = LlamaConfig(
        vocab_size=512, dim=512, n_layers=12, n_heads=8, n_kv_heads=4,
        mlp_dim=1536, max_len=2048, rope_theta=500_000.0,
    )
    return enc, llm


def run_eval(
    scale: str = "bench",
    n_docs: int = 1024,
    n_queries: int = 64,
    concurrency: int = 8,
    new_tokens: int = 48,
    verifier_tokens: int = 64,
    rtt_ms: float = 0.0,
    seed: int = 0,
    skip_baseline: bool = False,
    configs: Optional[set] = None,
    encoder_checkpoint: str = "",
    kv_quant: str = "none",
    verify_mode: str = "sync",
    verify_threshold: Optional[float] = None,
) -> dict:
    """Run the eval matrix; returns the EVAL.json payload (pure dict)."""
    import jax

    from sentio_tpu.config import (
        EmbedderConfig, GeneratorConfig, RerankConfig, Settings,
    )
    from sentio_tpu.graph.factory import GraphConfig, build_basic_graph
    from sentio_tpu.graph.state import create_initial_state
    from sentio_tpu.ops.bm25 import BM25Index
    from sentio_tpu.ops.dense_index import TpuDenseIndex
    from sentio_tpu.ops.embedder import TpuEmbedder
    from sentio_tpu.ops.generator import LLMGenerator, OpenAIProvider, TpuProvider
    from sentio_tpu.ops.reranker import CrossEncoderReranker
    from sentio_tpu.ops.retrievers import (
        DenseRetriever, HybridRetriever, SparseRetriever,
    )
    from sentio_tpu.ops.verifier import AnswerVerifier
    from sentio_tpu.runtime.engine import GeneratorEngine
    from sentio_tpu.runtime.paged import ContinuousBatchingEngine
    from sentio_tpu.runtime.replica import ReplicaSet
    from sentio_tpu.runtime.service import PagedGenerationService

    t_start = time.perf_counter()
    known = {"sparse_api", "dense", "hybrid_rerank", "full_paged", "batched"}
    want = set(configs) if configs else set(known)
    unknown = want - known
    if unknown:
        raise ValueError(f"unknown eval configs {sorted(unknown)}; known: {sorted(known)}")
    enc_cfg, llm_cfg = _build_models(scale)
    devices = jax.devices()
    _log(f"eval: {len(devices)} x {devices[0].platform} ({devices[0].device_kind}); "
         f"scale={scale} docs={n_docs} queries={n_queries} concurrency={concurrency}")

    bundle: EvalBundle = build_bundle(n_docs=n_docs, n_queries=n_queries, seed=seed)
    queries = bundle.queries

    settings = Settings()
    settings.generator.max_new_tokens = new_tokens
    # confidence-gated verification (ops/confidence.py): the verify quality
    # gate (tests/test_eval.py::TestVerifyGate) runs gated vs sync over the
    # SAME bundle/params and compares per-query verdicts
    settings.generator.verify_mode = verify_mode
    if verify_threshold is not None:
        settings.generator.verify_confidence_threshold = verify_threshold
    # the verifier emits a short JSON verdict; with random-init weights it
    # never hits EOS, so an uncapped budget would decode to the full default
    settings.generator.verifier_max_tokens = verifier_tokens
    # ByteTokenizer ≈ 1 token/char while the selector budget assumes 4
    # chars/token — size the doc budget so the ASSEMBLED prompt (docs +
    # instruction + question) fits the model window with generation headroom,
    # instead of letting paged admission truncate the prompt tail silently
    settings.generator.context_token_budget = max(
        (llm_cfg.max_len - new_tokens - 256) // 4, 32
    )
    settings.retrieval.top_k = 10
    # recall@10 must be measured over 10 documents end to end — the serving
    # default (rerank keeps 5) would silently turn the metric into recall@5
    settings.rerank.top_k = 10

    # ------------------------------------- shared stack (built only if used)
    needs_dense = bool(want & {"dense", "hybrid_rerank", "full_paged", "batched"})
    needs_sparse = bool(want & {"sparse_api", "hybrid_rerank", "full_paged", "batched"})
    rows: list[dict] = []
    extras: dict = {}

    embedder = dense_index = None
    if needs_dense:
        # trained weights (eval/train_encoder.py): the dense leg stops being
        # a random-init architecture statement and measures real retrieval
        # quality. The checkpoint's config applies to the EMBEDDER ONLY —
        # the reranker and mock-API server keep the scale's enc_cfg so the
        # rest of the matrix stays comparable to a no-checkpoint run.
        emb_params, emb_cfg = None, enc_cfg
        if encoder_checkpoint:
            from sentio_tpu.runtime.weights import load_model

            emb_params, emb_cfg, _ = load_model(
                encoder_checkpoint, expect_family="encoder"
            )
            extras["encoder_checkpoint"] = encoder_checkpoint
        _log("eval: embedding corpus on device ...")
        embedder = TpuEmbedder(
            EmbedderConfig(provider="tpu", batch_size=128),
            params=emb_params, model_config=emb_cfg,
        )
        t0 = time.perf_counter()
        vecs = embedder.embed_many([d.text for d in bundle.documents])
        ingest_s = time.perf_counter() - t0
        _log(f"eval: embedded {n_docs} docs in {ingest_s:.1f}s "
             f"({n_docs / max(ingest_s, 1e-9):.0f} docs/s)")
        dense_index = TpuDenseIndex(dim=emb_cfg.dim)
        dense_index.add(bundle.documents, vecs)
        extras["ingest_docs_per_s"] = round(n_docs / max(ingest_s, 1e-9), 1)
    bm25 = BM25Index().build(bundle.documents) if needs_sparse else None

    # ------------------------------------------- config 1: sparse + API LLM
    if "sparse_api" in want:
        from sentio_tpu.eval.baseline import MockModelServer

        server = MockModelServer(dim=enc_cfg.dim, rtt_ms=rtt_ms).start()
        try:
            sparse = SparseRetriever(bm25)
            api_gen = LLMGenerator(
                provider=OpenAIProvider(base_url=server.base_url + "/v1"),
                config=settings.generator,
            )

            def cfg1(question: str):
                docs = sparse.retrieve(question, top_k=10)
                answer = api_gen.generate(question, docs, mode="fast")
                return docs, answer

            _log("eval: [1/5] sparse_api ...")
            rows.append(run_queries("1-bm25+api-llm", cfg1, queries).row())
        finally:
            server.stop()

    # ------------------------------------------------ config 2: dense on TPU
    if "dense" in want:
        dense_ret = DenseRetriever(embedder, dense_index)

        def cfg2(question: str):
            return dense_ret.retrieve(question, top_k=10), ""

        _log("eval: [2/5] dense ...")
        rows.append(run_queries("2-dense-tpu", cfg2, queries).row())

    # ------------------------------- config 3: hybrid RRF + x-encoder rerank
    hybrid = reranker = None
    if want & {"hybrid_rerank", "full_paged", "batched"}:
        hybrid = HybridRetriever(
            retrievers=[DenseRetriever(embedder, dense_index), SparseRetriever(bm25)],
            config=settings.retrieval,
        )
        reranker = CrossEncoderReranker(RerankConfig(batch_size=32), model_config=enc_cfg)
    if "hybrid_rerank" in want:
        def cfg3(question: str):
            docs = hybrid.retrieve(question, top_k=10)
            return reranker.rerank(question, docs, top_k=10).documents, ""

        _log("eval: [3/5] hybrid_rerank ...")
        rows.append(run_queries("3-hybrid+rerank", cfg3, queries).row())

    # ---------------------- configs 4+5: full graph over paged continuous
    # batching (generator + verifier share one set of weights)
    service = None
    try:
        if want & {"full_paged", "batched"}:
            engine = GeneratorEngine(
                config=GeneratorConfig(model_preset="eval", max_new_tokens=new_tokens),
                model_config=llm_cfg,
            )
            paged = ContinuousBatchingEngine(
                model_config=llm_cfg,
                params=engine.params,
                tokenizer=engine.tokenizer,
                max_slots=max(concurrency, 4),
                page_size=16,
                # per-sequence window = the model's full context — prompts
                # sized by context_token_budget above always fit
                max_pages_per_seq=llm_cfg.max_len // 16,
                steps_per_tick=16,
                max_tick_steps=64,
                pipeline_depth=2,
                # int8 KV pages: the quality-gate run (tests/test_eval.py)
                # measures this config's recall/answers against bf16
                kv_quant=kv_quant,
                # random-init weights greedy-sample EOS almost immediately;
                # fixed-length generation keeps configs 4/5 measuring the
                # full decode+verify cost real tuned models pay
                ignore_eos=True,
            )
            # the serving tier's front-end, N=1: eval measures the same
            # routed path production serves (a degenerate single-replica
            # route is a pass-through, so config outputs stay pinned).
            # supervise=False: eval never closes the set, and a leaked
            # supervisor thread would outlive the config run
            service = ReplicaSet([PagedGenerationService(paged)],
                                 supervise=False)
            generator = LLMGenerator(
                provider=TpuProvider(engine=engine, service=service),
                config=settings.generator,
            )
            verifier = AnswerVerifier(generator=generator, config=settings.generator)
            graph = build_basic_graph(
                hybrid, generator, reranker=reranker, verifier=verifier,
                config=GraphConfig(settings=settings),
            )

            # answer metric for the quantization quality gate: mean emitted
            # answer length (chars) — a degenerate int8 decode (empty /
            # collapsed answers) moves this even when retrieval recall
            # cannot see it. list.append is atomic under the GIL, so the
            # concurrent "batched" config needs no extra lock.
            answer_chars: list[int] = []
            # per-question FINAL verdicts (async/gated verdicts are awaited
            # off the flight record) — what TestVerifyGate compares between
            # a gated and an always-verify run; dict so the harness warmup
            # repeat of question 0 just overwrites
            verdicts: dict[str, str] = {}

            def _await_verdict(query_id: str, timeout_s: float = 60.0):
                """Poll the flight record for a detached verify's verdict
                (VERIFY_MODE=async|gated leave the graph before the audit
                lands)."""
                from sentio_tpu.infra.flight import get_flight_recorder

                deadline = time.perf_counter() + timeout_s
                while time.perf_counter() < deadline:
                    rec = get_flight_recorder().get(query_id) or {}
                    outcome = rec.get("verify", {}).get("outcome")
                    if outcome is not None:
                        return outcome
                    time.sleep(0.05)
                return None

            def full(question: str):
                import uuid

                query_id = f"eval-{uuid.uuid4().hex[:10]}"
                state = graph.invoke(create_initial_state(
                    question, metadata={"mode": "fast", "query_id": query_id}
                ))
                docs = state.get("reranked_documents") or state.get("retrieved_documents") or []
                answer = state.get("response", "") or ""
                answer_chars.append(len(answer))
                verdict = (state.get("evaluation") or {}).get("verdict")
                if verdict is None and state.get("metadata", {}).get(
                        "verify_pending"):
                    verdict = _await_verdict(query_id)
                if verdict is not None:
                    verdicts[question] = str(verdict)
                return docs, answer

            if "full_paged" in want:
                _log("eval: [4/5] full_paged ...")
                answer_chars.clear()
                verdicts.clear()
                res4 = run_queries("4-full-graph-paged", full, queries)
                if answer_chars:
                    res4.extras["answer_chars_mean"] = round(
                        sum(answer_chars) / len(answer_chars), 1)
                if verdicts:
                    res4.extras["verdicts"] = dict(verdicts)
                    skipped = sum(1 for v in verdicts.values()
                                  if v == "skipped_confident")
                    res4.extras["verify_skip_rate"] = round(
                        skipped / len(verdicts), 4)
                rows.append(res4.row())
            if "batched" in want:
                _log(f"eval: [5/5] batched x{concurrency} ...")
                before = service.stats()  # stats are service-lifetime
                answer_chars.clear()
                result = run_queries(
                    "5-batched-dp", full, queries, concurrent=concurrency
                )
                if answer_chars:
                    result.extras["answer_chars_mean"] = round(
                        sum(answer_chars) / len(answer_chars), 1)
                stats = service.stats()
                ticks = stats["ticks"] - before["ticks"]
                active = (
                    stats["avg_active_slots"] * stats["ticks"]
                    - before["avg_active_slots"] * before["ticks"]
                )
                result.extras["avg_active_slots"] = round(active / max(ticks, 1), 3)
                result.extras["max_active_slots"] = stats["max_active_slots"]
                result.extras["decode_ticks"] = ticks
                rows.append(result.row())

        # ------------------------------------- measured reference baseline
        baseline_row = None
        if not skip_baseline:
            from sentio_tpu.eval.baseline import measure_baseline

            _log("eval: measuring reference-architecture loopback baseline ...")
            baseline = measure_baseline(
                bundle.documents, queries, dim=min(enc_cfg.dim, 1024), rtt_ms=rtt_ms
            )
            baseline_row = baseline.row()
    finally:
        if service is not None:
            # detached verify threads (VERIFY_MODE=async|gated) still hold
            # tickets on this service — join them before tearing it down
            from sentio_tpu.graph.executor import wait_detached

            wait_detached()
            service.close()

    payload: dict = {
        "metric": "synthetic NQ-style retrieval-QA: recall@10, p50 ms, QPS",
        "bundle": {"n_docs": n_docs, "n_queries": n_queries, "seed": seed,
                   "n_facts": bundle.n_facts},
        "platform": {
            "devices": len(devices),
            "kind": devices[0].device_kind,
            "backend": devices[0].platform,
        },
        "models": {
            "encoder": {"dim": enc_cfg.dim, "layers": enc_cfg.n_layers},
            "llm": {"dim": llm_cfg.dim, "layers": llm_cfg.n_layers,
                    "vocab": llm_cfg.vocab_size},
            "new_tokens": new_tokens,
        },
        "rows": rows,
        "baseline": baseline_row,
        "rtt_ms": rtt_ms,
        "wall_s": round(time.perf_counter() - t_start, 1),
        **({"kv_quant": kv_quant} if kv_quant != "none" else {}),
        **({"verify_mode": verify_mode} if verify_mode != "sync" else {}),
        **extras,
    }

    # the north-star comparison: full graph p50 vs the measured baseline p50
    full_row = next((r for r in rows if r["config"].startswith("4-")), None)
    if full_row and baseline_row:
        payload["north_star"] = {
            "target_speedup": 10.0,
            "measured_p50_speedup": round(
                baseline_row["p50_ms"] / max(full_row["p50_ms"], 1e-9), 2
            ),
            "recall_delta": round(
                full_row["recall@10"] - baseline_row["recall@10"], 3
            ),
        }
    return payload
