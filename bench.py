"""End-to-end RAG serving benchmark — runs on whatever jax.devices() offers
(the driver runs it on one real TPU chip; CPU works for smoke tests).

Three phases, all through the DEFAULT serving path (paged KV continuous
batching — concurrent callers share fused decode dispatches):

A. **RAG e2e** — the full retrieve → rerank → select → generate → verify
   graph with every model in-process on the device, driven by N concurrent
   clients. Reports per-request p50/p95, QPS, per-node p50 breakdown, and
   decode-batch occupancy.
B. **Measured baseline** — the reference's architecture shape (HTTP hops to
   loopback mock models, python-loop retrieval math; eval/baseline.py) over
   the SAME corpus and queries. ``vs_baseline`` is measured-vs-measured: a
   deliberate LOWER bound for the reference (zero network latency, zero
   model compute — real deployments add 10-400 ms WAN per hop).
C. **Decode at scale** — continuous-batched generation on the largest
   Llama-class model that fits single-chip HBM in bf16 (~1.4B by default,
   BENCH_SERVE_SCALE=8b for an 8B-layer-geometry variant), reporting
   tokens/s, MFU (= tok/s x 2 x params / peak bf16 FLOPs), and HBM
   bandwidth utilization of the decode loop.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.
Details go to stderr.

Env knobs: BENCH_FAST=1 (tiny models, quick smoke), BENCH_QUERIES=N,
BENCH_CORPUS=N, BENCH_NEW_TOKENS=N, BENCH_CONCURRENCY=N,
BENCH_SKIP_SCALE=1 (skip phase C), BENCH_SERVE_SCALE=1b|8b|moe,
BENCH_SCALE_TOKENS=N, BENCH_SPECULATIVE=1 (add phase E: plain-vs-
speculative decode on the serve-scale target, greedy-exact),
BENCH_VERIFY_SWEEP=1 (phase A once per VERIFY_MODE — sync|async|gated —
reporting p50/p95 e2e, the answer_ms/verdict_ms split, and the gate skip
rate; BENCH_VERIFY_THRESHOLD overrides the confidence gate).
"""

from __future__ import annotations

import json
import os
import sys
import time

# v5e peak specs for the MFU / bandwidth denominators
PEAK_BF16_FLOPS = 197e12
PEAK_HBM_GBS = 819.0


def log(*args) -> None:
    print(*args, file=sys.stderr, flush=True)


def device_platform() -> str:
    """cpu | tpu | gpu — stamped into EVERY artifact section so a fallback
    round can never again be mistaken for a device round (the r04–r06
    "not comparable to the TPU baseline" confusion, made structural)."""
    import jax

    return jax.default_backend()


def warn_cpu_fallback(reason: str) -> None:
    """Loud, unmissable stderr banner when a TPU-requested run fell back
    to host CPU. Printed at fallback time AND as the run's last stderr
    output so it cannot scroll away under phase logs."""
    bar = "!" * 72
    log(bar)
    log("!! BENCH FELL BACK TO CPU — AN ACCELERATOR WAS REQUESTED")
    log(f"!! reason: {reason}")
    log("!! These numbers are NOT comparable to TPU rounds (BENCH_r01-r03).")
    log("!! The artifact is marked device_fallback, and device_platform=cpu")
    log("!! is stamped into every section.")
    log(bar)


def build_corpus(n: int) -> list:
    from sentio_tpu.models.document import Document

    topics = [
        ("tpu", "TPU v5e chips pair a 128x128 MXU systolic array with {i} MiB of VMEM; "
                "matmul throughput peaks in bfloat16 when tiles stay MXU-aligned."),
        ("jax", "JAX traces pure functions into XLA programs; version {i} introduced "
                "sharding improvements for pjit and shard_map collectives."),
        ("rag", "Retrieval augmented generation pipeline number {i} fuses BM25 with "
                "dense retrieval and reranks candidates before generation."),
        ("ir", "Classic information retrieval experiment {i} shows BM25 term "
               "saturation controlled by k1 and length normalization by b."),
        ("net", "Inter-chip interconnect study {i}: ring all-reduce bandwidth scales "
                "with torus links while DCN hops dominate cross-slice latency."),
    ]
    docs = []
    for i in range(n):
        key, template = topics[i % len(topics)]
        docs.append(
            Document(
                text=template.replace("{i}", str(i)),
                id=f"{key}-{i}",
                metadata={"source": f"{key}.md"},
            )
        )
    return docs


def count_params(params) -> int:
    import jax

    return sum(x.size for x in jax.tree_util.tree_leaves(params))


def _percentile(vals, q):
    vals = sorted(vals)
    if not vals:
        return 0.0
    return vals[min(int(len(vals) * q), len(vals) - 1)]


def _flight_artifacts():
    """Fold the flight recorder + TTFT/TPOT histograms into artifact form:
    the tick-level occupancy timeline (downsampled to <= 160 events) with
    summary percentiles, and the per-sequence TTFT/TPOT distributions. This
    is the round-5 fix: the committed BENCH json now carries the engine's
    own per-tick record of what the decode batch did, not prose."""
    from sentio_tpu.infra.flight import get_flight_recorder
    from sentio_tpu.infra.metrics import get_metrics

    snap = get_flight_recorder().snapshot()
    ticks = snap["ticks"]
    out = {"ticks": {"n": snap["ticks_recorded"], "retained": len(ticks)}}
    if ticks:
        occ = [t.get("active_slots", 0) for t in ticks]
        dur = [t.get("dur_ms", 0.0) for t in ticks]
        queue = [t.get("queue_depth", 0) + t.get("inbox_depth", 0) for t in ticks]
        out["ticks"].update({
            "occupancy_mean": round(sum(occ) / len(occ), 2),
            "occupancy_max": max(occ),
            "dur_p50_ms": round(_percentile(dur, 0.50), 2),
            "dur_p95_ms": round(_percentile(dur, 0.95), 2),
            "queue_depth_p95": _percentile(queue, 0.95),
            "prefill_tokens": sum(t.get("prefill_tokens", 0) for t in ticks),
            "decode_tokens": sum(t.get("decode_tokens", 0) for t in ticks),
        })
        stride = -(-len(ticks) // 160)  # ceil: keeps the timeline <= 160 events
        out["ticks"]["timeline"] = [
            {"t_s": t["t_s"], "active": t.get("active_slots", 0),
             "queued": t.get("queue_depth", 0) + t.get("inbox_depth", 0),
             "free_pages": t.get("free_pages")}
            for t in ticks[::stride]
        ]
    histos = get_metrics().memory.snapshot()["histograms"]
    for label, key in (("ttft_ms", "ttft"), ("tpot_ms", "tpot")):
        merged = [h for k, h in histos.items() if k.startswith(key + "(")]
        if merged:
            h = merged[0]  # one path label in-bench ("paged")
            out[label] = {
                "p50": round(h["p50"] * 1e3, 3),
                "p95": round(h["p95"] * 1e3, 3),
                "mean": round(h["mean"] * 1e3, 3),
                "n": h["count"],
                "dropped": h["dropped"],
            }
    return out


def phase_0_rtt():
    """Raw host↔device round-trip cost: dispatch a trivial jitted op on a
    1-element array and fetch the result. Through a remote-attached chip
    this is ~RTT of the tunnel and bounds every per-tick/per-fetch cost in
    the phases below; on a locally attached chip it is sub-ms. Published so
    a slow-tunnel day is visible IN the artifact instead of silently
    inflating every latency number."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    f = jax.jit(lambda x: x + 1.0)
    x = jnp.zeros((1,), jnp.float32)
    np.asarray(f(x))  # compile
    samples = []
    for _ in range(10):
        t0 = time.perf_counter()
        np.asarray(f(x))
        samples.append((time.perf_counter() - t0) * 1000.0)
    out = {
        "device_rtt_p50_ms": round(_percentile(samples, 0.50), 1),
        "device_rtt_min_ms": round(min(samples), 1),
    }
    log(f"phase 0: device round-trip p50={out['device_rtt_p50_ms']}ms "
        f"min={out['device_rtt_min_ms']}ms")
    return out


def phase_a_rag(settings, enc_cfg, llm_cfg, docs, queries, n_queries,
                new_tokens, concurrency, kv_quant="none", verify_mode=None):
    """Full graph with paged continuous batching, N concurrent clients.

    ``verify_mode`` (sync|async|gated, default = the settings tree's value)
    rebuilds the graph with that verification wiring — the
    BENCH_VERIFY_SWEEP driver runs this phase once per mode on the same
    corpus/queries so the off-critical-path claim lands as measurement."""
    import threading
    from dataclasses import replace as _dc_replace

    from sentio_tpu.config import EmbedderConfig, GeneratorConfig, RerankConfig
    from sentio_tpu.graph.factory import GraphConfig, build_basic_graph
    from sentio_tpu.graph.state import create_initial_state
    from sentio_tpu.ops.bm25 import BM25Index
    from sentio_tpu.ops.dense_index import TpuDenseIndex
    from sentio_tpu.ops.embedder import TpuEmbedder
    from sentio_tpu.ops.generator import LLMGenerator, TpuProvider
    from sentio_tpu.ops.reranker import CrossEncoderReranker
    from sentio_tpu.ops.retrievers import DenseRetriever, HybridRetriever, SparseRetriever
    from sentio_tpu.ops.verifier import AnswerVerifier
    from sentio_tpu.runtime.engine import GeneratorEngine
    from sentio_tpu.runtime.paged import ContinuousBatchingEngine
    from sentio_tpu.runtime.service import PagedGenerationService

    if verify_mode is not None:
        settings = settings.with_overrides(
            generator=_dc_replace(settings.generator, verify_mode=verify_mode)
        )
    verify_mode = settings.generator.verify_mode

    log("phase A: building corpus + indexes ...")
    embedder = TpuEmbedder(
        EmbedderConfig(provider="tpu", batch_size=128), model_config=enc_cfg
    )
    t0 = time.perf_counter()
    corpus_vecs = embedder.embed_many([d.text for d in docs])
    embed_s = time.perf_counter() - t0
    docs_per_s = len(docs) / max(embed_s, 1e-9)
    log(f"  embedded {len(docs)} docs in {embed_s:.1f}s ({docs_per_s:.0f} docs/s)")

    dense_index = TpuDenseIndex(dim=enc_cfg.dim)
    dense_index.add(docs, corpus_vecs)
    bm25 = BM25Index().build(docs)
    retriever = HybridRetriever(
        retrievers=[DenseRetriever(embedder, dense_index), SparseRetriever(bm25)],
        config=settings.retrieval,
    )
    reranker = CrossEncoderReranker(RerankConfig(batch_size=32), model_config=enc_cfg)
    engine = GeneratorEngine(
        config=GeneratorConfig(model_preset="bench", max_new_tokens=new_tokens),
        model_config=llm_cfg,
    )
    paged = ContinuousBatchingEngine(
        model_config=llm_cfg, params=engine.params, tokenizer=engine.tokenizer,
        max_slots=max(concurrency, 4), page_size=16,
        max_pages_per_seq=llm_cfg.max_len // 16, steps_per_tick=16,
        max_tick_steps=64, pipeline_depth=2, kv_quant=kv_quant,
        # random-init weights greedy-sample EOS almost immediately — fixed-
        # length generation measures the cost real tuned models actually pay
        ignore_eos=True,
    )
    service = PagedGenerationService(paged)
    generator = LLMGenerator(
        provider=TpuProvider(engine=engine, service=service), config=settings.generator
    )
    verifier = AnswerVerifier(generator=generator, config=settings.generator)
    graph = build_basic_graph(
        retriever, generator, reranker=reranker, verifier=verifier,
        config=GraphConfig(settings=settings),
    )

    log("phase A: warmup (compilation, full-concurrency burst) ...")
    t0 = time.perf_counter()
    warm_threads = [
        threading.Thread(
            target=graph.invoke,
            args=(create_initial_state(queries[i % len(queries)], metadata={"mode": "fast"}),),
        )
        for i in range(concurrency)
    ]
    for t in warm_threads:
        t.start()
    for t in warm_threads:
        t.join()
    log(f"  warmup done in {time.perf_counter() - t0:.1f}s")

    # drain the warmup pump, then zero the flight recorder + metrics so the
    # embedded tick timeline / TTFT-TPOT distributions cover ONLY the timed
    # run (warmup ticks carry multi-second jit compiles)
    from sentio_tpu.infra.flight import get_flight_recorder
    from sentio_tpu.infra.metrics import MetricsCollector, set_metrics

    t_drain = time.perf_counter()
    while service._pump is not None and service._pump.is_alive():
        if time.perf_counter() - t_drain > 10.0:
            break
        time.sleep(0.01)

    # compile accounting over the TIMED window (analysis/audit/fence.py):
    # warmup pays the jit cost up front, so a steady-state run should
    # report xla_compiles == 0 — any other number is a recompile the
    # latency percentiles silently absorbed. SENTIO_COMPILE_FENCE=1 arms
    # the fence so such a recompile fails the bench outright; the graph
    # burst above only compiled the variants its prompts happened to hit,
    # so the declared width/prior buckets are warmed explicitly first.
    from sentio_tpu.analysis.audit import fence

    if fence.enabled():
        service.warmup()
    get_flight_recorder().clear()
    set_metrics(MetricsCollector())
    compiles_before = fence.compiles_total()
    if fence.enabled():
        fence.arm()

    latencies: list[float] = []
    lat_pairs: list[tuple[int, float]] = []
    node_ms: dict[str, list[float]] = {}
    lock = threading.Lock()
    pending = [(i, queries[i % len(queries)]) for i in range(n_queries)]
    stats_before = service.stats()

    def worker():
        while True:
            with lock:
                if not pending:
                    return
                i, q = pending.pop()
            t0 = time.perf_counter()
            # ids namespaced per verify mode: the recorder is cleared per
            # phase run, but a sweep must never risk one mode's late
            # verify record merging onto another mode's id
            state = graph.invoke(create_initial_state(
                q, metadata={"mode": "fast",
                             "query_id": f"bench-{verify_mode}-{i}"}
            ))
            dt = (time.perf_counter() - t0) * 1000.0
            with lock:
                latencies.append(dt)
                lat_pairs.append((i, dt))
                for node, ms in (state["metadata"].get("node_timings_ms") or {}).items():
                    node_ms.setdefault(node, []).append(ms)

    t_run = time.perf_counter()
    threads = [threading.Thread(target=worker) for _ in range(concurrency)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t_run
    # detached verifies (async/gated) still decode on this service — join
    # them before closing it, and so their verdict_ms land on the records
    from sentio_tpu.graph.executor import wait_detached

    wait_detached(timeout_s=120.0)
    stats = service.stats()
    if fence.enabled():
        fence.disarm()
    xla_compiles = fence.compiles_total() - compiles_before
    service.close()

    # answer vs verdict split (ISSUE 11): answer_ms is what the CALLER
    # waited for the answer (graph invoke — under async/gated the graph
    # returns at the gate, so verify is already excluded; under sync the
    # recorded verdict_ms is subtracted out), verdict_ms is the audit
    # decode wherever it ran. gate_skip_rate counts skipped_confident.
    recorder = get_flight_recorder()
    answer_ms_list: list[float] = []
    verdict_ms_list: list[float] = []
    skipped = 0
    verified = 0
    for i, dt in lat_pairs:
        verify_rec = (recorder.get(f"bench-{verify_mode}-{i}") or {}).get(
            "verify") or {}
        vms = verify_rec.get("verdict_ms")
        outcome = verify_rec.get("outcome")
        if outcome == "skipped_confident":
            skipped += 1
        elif outcome in ("pass", "warn", "fail"):
            # real audit verdicts only: deadline/empty skips are neither a
            # gate payoff nor a completed verification and must not skew
            # the reported gate_skip_rate
            verified += 1
        if vms is not None:
            verdict_ms_list.append(float(vms))
        answer_ms_list.append(
            dt - float(vms) if verify_mode == "sync" and vms is not None
            else dt
        )

    ticks = stats["ticks"] - stats_before["ticks"]
    active = stats["avg_active_slots"] * stats["ticks"] - (
        stats_before["avg_active_slots"] * stats_before["ticks"]
    )
    result = {
        "p50_ms": round(_percentile(latencies, 0.50), 1),
        "p95_ms": round(_percentile(latencies, 0.95), 1),
        "qps": round(len(latencies) / wall, 2),
        "concurrency": concurrency,
        "n_queries": len(latencies),
        "node_p50_ms": {
            k: round(_percentile(v, 0.50), 1) for k, v in sorted(node_ms.items())
        },
        # per-node percentiles WITH sample counts (round-5 verdict: a p50
        # without its n is prose) + the flight recorder's tick timeline and
        # TTFT/TPOT distributions — the artifact carries its own evidence
        "node_percentiles": {
            k: {"p50_ms": round(_percentile(v, 0.50), 1),
                "p95_ms": round(_percentile(v, 0.95), 1),
                "n": len(v)}
            for k, v in sorted(node_ms.items())
        },
        **_flight_artifacts(),
        "verify": {
            "mode": verify_mode,
            "answer_ms": {
                "p50": round(_percentile(answer_ms_list, 0.50), 1),
                "p95": round(_percentile(answer_ms_list, 0.95), 1),
                "n": len(answer_ms_list),
            },
            "verdict_ms": {
                "p50": round(_percentile(verdict_ms_list, 0.50), 1),
                "p95": round(_percentile(verdict_ms_list, 0.95), 1),
                "n": len(verdict_ms_list),
            },
            "gate_skip_rate": round(
                skipped / max(skipped + verified, 1), 4),
            "skipped": skipped,
            "verified": verified,
        },
        "avg_active_slots": round(active / max(ticks, 1), 2),
        "max_active_slots": stats["max_active_slots"],
        "ingest_docs_per_s": round(docs_per_s, 1),
        "xla_compiles": xla_compiles,
        # footprint next to latency: the int8-vs-bf16 claim rides the
        # artifact as measurement (BENCH_KV_QUANT_SWEEP runs both)
        "kv_quant": kv_quant,
        "pool_hbm_bytes": paged.pool.hbm_bytes,
    }
    # radix prefix cache: fraction of admitted prompt tokens served
    # read-only from cached KV over the TIMED window (the before/after
    # deltas exclude the warmup burst, which both seeds the cache and
    # hits it at 100% on its repeats)
    hit = stats.get("prefix_hit_tokens", 0) - stats_before.get("prefix_hit_tokens", 0)
    miss = stats.get("prefix_miss_tokens", 0) - stats_before.get("prefix_miss_tokens", 0)
    if hit + miss:
        result["prefix_hit_token_ratio"] = round(hit / (hit + miss), 4)
    log(f"phase A: p50={result['p50_ms']}ms p95={result['p95_ms']}ms "
        f"qps={result['qps']} occupancy={result['avg_active_slots']} "
        f"nodes={result['node_p50_ms']} "
        f"ttft={result.get('ttft_ms')} tpot={result.get('tpot_ms')} "
        f"prefix_hit={result.get('prefix_hit_token_ratio')} "
        f"xla_compiles={result['xla_compiles']}")
    return result


def phase_b_baseline(docs, queries, n_queries, dim, rtt_ms=0.0):
    """Reference-architecture loopback baseline on the same corpus/queries.
    ``rtt_ms`` > 0 injects a per-hop delay approximating WAN latency to the
    remote model APIs the reference actually calls (still zero model
    compute, so even the rtt variant is a lower bound)."""
    from sentio_tpu.eval.baseline import measure_baseline

    log(f"phase B: measuring reference-architecture loopback baseline "
        f"(rtt={rtt_ms:.0f}ms) ...")
    qs = [(queries[i % len(queries)], "na") for i in range(n_queries)]
    result = measure_baseline(docs, qs, dim=dim, rtt_ms=rtt_ms)
    log(f"phase B: baseline(rtt={rtt_ms:.0f}) p50={result.p50_ms:.1f}ms "
        f"qps={result.qps:.2f} (zero model compute)")
    return {
        "p50_ms": round(result.p50_ms, 1),
        "p95_ms": round(result.p95_ms, 1),
        "qps": round(result.qps, 2),
        "rtt_ms": rtt_ms,
        "http_calls": result.extras.get("http_calls", {}),
    }


def serve_scale_config(kind: str):
    from sentio_tpu.models.llama import LlamaConfig

    if kind == "8b":
        # Llama-3-8B layer geometry (dim 4096 / mlp 14336 / GQA 32:8), layer
        # count cut to fit 16 GB HBM with the KV pool: ~3.5B params ~ 7 GB
        return LlamaConfig(
            vocab_size=32_000, dim=4096, n_layers=12, n_heads=32, n_kv_heads=8,
            mlp_dim=14_336, max_len=2048, rope_theta=500_000.0,
        )
    if kind == "moe":
        # Mixtral-style sparse geometry: ~2.6B total params but only ~0.8B
        # active per token (top-2 of 8 experts) — decode streams the full
        # expert weights, so tok/s vs the dense 1b shows the routing cost
        from sentio_tpu.models.moe import MoeConfig

        return MoeConfig(
            vocab_size=32_000, dim=1024, n_layers=12, n_heads=16, n_kv_heads=8,
            mlp_dim=4096, max_len=2048, rope_theta=500_000.0,
            n_experts=8, experts_per_token=2,
        )
    # ~1.4B: MXU-aligned dims, GQA 16:8
    return LlamaConfig(
        vocab_size=32_000, dim=2048, n_layers=16, n_heads=16, n_kv_heads=8,
        mlp_dim=8192, max_len=2048, rope_theta=500_000.0,
    )


def phase_c_scale(kind: str, new_tokens: int, concurrency: int,
                  kv_quant: str = "none"):
    """Continuous-batched decode throughput at HBM-filling model scale."""
    import threading

    from sentio_tpu.runtime.paged import ContinuousBatchingEngine
    from sentio_tpu.runtime.service import PagedGenerationService

    import jax

    from sentio_tpu.models.llama import init_llama
    from sentio_tpu.models.moe import MoeConfig, init_moe

    cfg = serve_scale_config(kind)
    init_fn = init_moe if isinstance(cfg, MoeConfig) else init_llama
    log(f"phase C: init {kind} serve-scale model "
        f"(dim={cfg.dim} L={cfg.n_layers} vocab={cfg.vocab_size}) ...")
    t0 = time.perf_counter()
    # store weights in bf16 (init samples f32; converted checkpoints
    # arrive bf16 — f32 residency would put the 8b geometry over HBM).
    # jit fuses init+cast so only the bf16 tree materializes; an eager
    # tree_map would hold BOTH trees (17 GB) and thrash the allocator.
    init_bf16 = jax.jit(
        lambda key: jax.tree_util.tree_map(
            lambda x: x.astype(cfg.jdtype), init_fn(key, cfg)
        )
    )
    params = init_bf16(jax.random.PRNGKey(0))
    jax.block_until_ready(params)
    window = 512 if kind == "8b" else 1024
    engine = ContinuousBatchingEngine(
        model_config=cfg, params=params, max_slots=concurrency, page_size=16,
        max_pages_per_seq=window // 16, steps_per_tick=16, kv_quant=kv_quant,
        # one compiled tick size for the 8b smoke — its scan compile through
        # the remote-compile service runs minutes per variant
        max_tick_steps=16 if kind == "8b" else 64,
        pipeline_depth=2, ignore_eos=True,
    )
    n_params = count_params(engine.params)
    log(f"  {n_params / 1e9:.2f}B params on device in {time.perf_counter() - t0:.1f}s")

    prompt = ("Benchmark prompt: explain how a systolic array performs matrix "
              "multiplication and why bfloat16 doubles its throughput. " * 3)
    service = PagedGenerationService(engine)
    log("phase C: warmup (compilation, full-concurrency burst) ...")
    t0 = time.perf_counter()
    warm = {}

    def warm_worker(i):
        warm[i] = service.generate(prompt, max_new_tokens=engine.max_tick_steps)

    threads = [threading.Thread(target=warm_worker, args=(i,)) for i in range(concurrency)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    log(f"  warmup done in {time.perf_counter() - t0:.1f}s")

    results = {}

    def worker(i):
        results[i] = service.generate(
            prompt + f" variant {i}", max_new_tokens=new_tokens, temperature=0.0
        )

    stats_before = service.stats()
    sub_steps_before = engine.total_sub_steps
    t0 = time.perf_counter()
    threads = [threading.Thread(target=worker, args=(i,)) for i in range(concurrency)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    stats = service.stats()
    service.close()

    total_tokens = sum(len(r.tokens) for r in results.values())
    tok_s = total_tokens / wall
    # each executed device sub-step streams the weights once (the fused scan
    # runs its full static length regardless of per-row halting)
    steps_s = max(engine.total_sub_steps - sub_steps_before, 1) / wall
    weight_bytes = n_params * 2
    out = {
        "model": kind,
        "params_b": round(n_params / 1e9, 2),
        "tokens": total_tokens,
        "wall_s": round(wall, 2),
        "tokens_per_s": round(tok_s, 1),
        "mfu_pct": round(tok_s * 2 * n_params / PEAK_BF16_FLOPS * 100, 3),
        # decode is bandwidth-bound: each fused step streams the weights once
        "hbm_util_pct": round(steps_s * weight_bytes / (PEAK_HBM_GBS * 1e9) * 100, 1),
        "concurrency": concurrency,
        "max_active_slots": stats["max_active_slots"],
        "kv_quant": kv_quant,
        "pool_hbm_bytes": engine.pool.hbm_bytes,
    }
    log(f"phase C: {out['tokens_per_s']} tok/s on {out['params_b']}B params "
        f"(MFU {out['mfu_pct']}%, HBM {out['hbm_util_pct']}%) over {wall:.1f}s")
    return out


def phase_e_speculative(kind: str, new_tokens: int):
    """Plain vs speculative greedy decode on the serve-scale target with a
    4-layer draft (same vocab). Opt-in (BENCH_SPECULATIVE=1): adds ~2 model
    inits + 2 bulk generates of chip time. Exactness is asserted, so the
    speedup column can be trusted as same-output."""
    import jax

    from sentio_tpu.config import GeneratorConfig
    from sentio_tpu.models.llama import LlamaConfig, init_llama
    from sentio_tpu.runtime.engine import GeneratorEngine
    from sentio_tpu.runtime.speculative import SpeculativeDecoder

    cfg = serve_scale_config(kind)
    if type(cfg) is not LlamaConfig:
        log("phase E: speculative bench supports dense targets only; skipping")
        return None
    log(f"phase E: speculative decode, {kind} target + 4-layer draft ...")
    init_bf16 = jax.jit(
        lambda key, c=cfg: jax.tree_util.tree_map(
            lambda x: x.astype(c.jdtype), init_llama(key, c)
        )
    )
    engine = GeneratorEngine(
        config=GeneratorConfig(model_preset="bench", max_new_tokens=new_tokens),
        model_config=cfg, params=init_bf16(jax.random.PRNGKey(0)),
    )
    draft_cfg = LlamaConfig(
        vocab_size=cfg.vocab_size, dim=cfg.dim // 2, n_layers=4,
        n_heads=cfg.n_heads // 2, n_kv_heads=max(cfg.n_kv_heads // 2, 1),
        mlp_dim=cfg.mlp_dim // 2, max_len=cfg.max_len,
        rope_theta=cfg.rope_theta,
    )
    draft_params = jax.jit(
        lambda key: jax.tree_util.tree_map(
            lambda x: x.astype(draft_cfg.jdtype), init_llama(key, draft_cfg)
        )
    )(jax.random.PRNGKey(1))
    spec = SpeculativeDecoder(engine, draft_params, draft_cfg, k=4)

    prompts = ["Explain how paged attention amortizes page table walks."] * 4
    # warmup both paths at the TIMED step count — `steps` is a jit static
    # arg, so a shorter warmup would push the full-length compile into the
    # timed region and the "speedup" would compare compile times
    engine.generate(prompts, max_new_tokens=new_tokens, temperature=0.0)
    spec.generate(prompts, max_new_tokens=new_tokens)
    spec.stats = {"rounds": 0, "tokens": 0}  # acceptance stats: timed run only

    t0 = time.perf_counter()
    plain = engine.generate(prompts, max_new_tokens=new_tokens, temperature=0.0)
    plain_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    fast = spec.generate(prompts, max_new_tokens=new_tokens)
    spec_s = time.perf_counter() - t0
    # greedy-exactness holds up to argmax ties under float reassociation
    # (T=1 decode vs T=k+1 verify reduce in different orders); report any
    # divergence rather than aborting the whole bench after the expensive
    # phases already ran
    mismatched = sum(
        f.tokens != p.tokens for f, p in zip(fast, plain)
    )

    out = {
        "plain_tok_s": round(sum(len(r.tokens) for r in plain) / plain_s, 1),
        "spec_tok_s": round(sum(len(r.tokens) for r in fast) / spec_s, 1),
        "speedup": round(plain_s / max(spec_s, 1e-9), 2),
        "tokens_per_verify": round(spec.tokens_per_round, 2),
        "mismatched_rows": mismatched,
    }
    log(f"phase E: {out}")
    return out


def phase_f_longctx(new_tokens: int = 32):
    """8K-window serving measurement — the reference's hardest limit made a
    number. The reference truncates every prompt to ~2000 tokens
    (/root/reference/src/core/graph/nodes.py:296-338, factory.py:90 there);
    here a ~6K-token prompt prefills through the paged engine untruncated
    and decodes at full context. Reports prefill TTFT and e2e p50."""
    from sentio_tpu.models.llama import LlamaConfig
    from sentio_tpu.runtime.paged import ContinuousBatchingEngine

    cfg = LlamaConfig(
        vocab_size=512, dim=512, n_layers=12, n_heads=8, n_kv_heads=4,
        mlp_dim=1536, max_len=8192, rope_theta=500_000.0,
    )
    pages = 8192 // 32
    eng = ContinuousBatchingEngine(
        model_config=cfg, max_slots=2, page_size=32, max_pages_per_seq=pages,
        num_pages=1 + 2 * pages, steps_per_tick=16, max_tick_steps=32,
        pipeline_depth=2, ignore_eos=True,
    )
    words = ("pallas mesh ring paged tick fuse shard scan hbm mxu "
             "systolic bfloat collective permute lane sublane ")
    prompt = (words * 90)[:6100]  # ~6.1K tokens under the byte tokenizer
    log("phase F: long-context warmup (6K-token prefill compile) ...")
    t0 = time.perf_counter()
    eng.run_all([prompt], max_new_tokens=2)
    log(f"  warmup done in {time.perf_counter() - t0:.1f}s")
    # drop the warmup's compile-inflated TTFT sample so the reported p50
    # covers only the measured runs
    eng.ttft_samples.clear()
    times = []
    res = None
    for _ in range(3):
        t0 = time.perf_counter()
        [res] = eng.run_all([prompt], max_new_tokens=new_tokens)
        times.append((time.perf_counter() - t0) * 1e3)
    stats = eng.stats()
    times.sort()
    p50 = times[len(times) // 2]
    ttft = stats.get("ttft_p50_ms") or 0.0
    out = {
        "prompt_tokens": res.prompt_tokens,
        "window": cfg.max_len,
        "p50_ms": round(p50, 1),            # prefill + new_tokens, e2e
        "ttft_p50_ms": round(ttft, 1),      # submit → first token visible
        # decode-only rate once the 6K-token prefill is paid; suppressed
        # when cross-run variance puts the TTFT median past the e2e median
        # (they come from different percentile pools)
        "decode_tok_s": round((new_tokens - 1) / ((p50 - ttft) / 1e3), 1)
        if ttft and p50 > ttft else None,
    }
    log(f"phase F longctx: {out}")
    return out


def phase_load(llm_cfg, new_tokens):
    """Open-loop load harness (BENCH_LOAD=1): a Poisson arrival stream of
    concurrent generate ("/chat"-shaped) + streaming ("SSE"-shaped) requests
    against the multi-replica serving tier, swept over an offered-QPS ladder
    and over replica counts. Open-loop means arrivals do NOT wait for
    completions — in-flight requests pile past any fixed client count, which
    is the regime the n=32/c=8 closed-loop phases can never reach. Reports
    per-level SLO attainment (p50/p95/p99 e2e, stream TTFT/TPOT), shed and
    expired rates, the highest offered QPS sustained at a shed-rate SLO,
    per-replica ``prefix_hit_token_ratio`` (requests carry session heads, so
    radix-affinity routing is exercised and measured), and a two-turn
    session affinity probe whose second request must report
    ``prefix_hit_tokens > 0`` on the routed replica.

    ``BENCH_LOAD_MODES`` sweeps the replica ISOLATION tier: "thread" (all
    N pumps in this process — the GIL-bound baseline) and/or "process"
    (each replica a spawned worker process behind the RPC shim,
    runtime/worker.py). With both, the artifact reports the GIL probe PER
    MODE side by side: per-replica host fractions and the sustained-QPS
    scaling ratio — the direct measurement of what escaping the GIL buys
    (ROADMAP item 1).

    Env knobs: BENCH_LOAD_REPLICAS ("1,2"), BENCH_LOAD_QPS ladder
    ("2,4,8,16,32"), BENCH_LOAD_SECONDS per level (8), BENCH_LOAD_SLOTS
    per-replica decode slots (8), BENCH_LOAD_SHED_SLO (0.05),
    BENCH_LOAD_SEED (1234), BENCH_LOAD_MODES ("thread" |
    "thread,process")."""
    import random
    import threading

    from sentio_tpu.infra.exceptions import (
        DeadlineExceededError,
        ServiceOverloaded,
    )
    from sentio_tpu.infra.flight import get_flight_recorder
    from sentio_tpu.infra.metrics import MetricsCollector, set_metrics
    from sentio_tpu.runtime.paged import ContinuousBatchingEngine
    from sentio_tpu.runtime.replica import ReplicaSet
    from sentio_tpu.runtime.service import PagedGenerationService

    replica_counts = sorted({
        int(x) for x in os.environ.get("BENCH_LOAD_REPLICAS", "1,2").split(",")
        if x.strip()
    })
    qps_ladder = [float(x)
                  for x in os.environ.get("BENCH_LOAD_QPS",
                                          "2,4,8,16,32").split(",")
                  if x.strip()]
    level_s = float(os.environ.get("BENCH_LOAD_SECONDS", "8"))
    shed_slo = float(os.environ.get("BENCH_LOAD_SHED_SLO", "0.05"))
    max_slots = int(os.environ.get("BENCH_LOAD_SLOTS", "8"))
    seed = int(os.environ.get("BENCH_LOAD_SEED", "1234"))
    replica_modes = [m.strip().lower()
                     for m in os.environ.get("BENCH_LOAD_MODES",
                                             "thread").split(",")
                     if m.strip()]
    gen_tokens = min(new_tokens, 16)
    stream_frac = 0.3

    # engines are reused across replica counts (compile once); reset()
    # clears pool/radix so every run starts cold
    engines: list = []

    def get_engines(n: int) -> list:
        while len(engines) < n:
            engines.append(ContinuousBatchingEngine(
                model_config=llm_cfg,
                params=engines[0].params if engines else None,
                tokenizer=engines[0].tokenizer if engines else None,
                max_slots=max_slots, page_size=16, max_pages_per_seq=8,
                steps_per_tick=8, max_tick_steps=8, pipeline_depth=2,
                ignore_eos=True,
            ))
        for eng in engines[:n]:
            eng.reset()
        return engines[:n]

    def build_replicas(mode: str, n: int) -> list:
        """N replicas at the requested isolation tier. Thread mode reuses
        the shared-weights in-process engines (compile once across counts);
        process mode spawns fresh worker processes — compiles are per
        worker by construction, which is part of what the mode costs."""
        if mode == "process":
            import dataclasses as _dc

            from sentio_tpu.models.tokenizer import ByteTokenizer
            from sentio_tpu.runtime.worker import ProcessReplica, WorkerSpec

            spec = WorkerSpec(factory_kwargs=dict(
                model_config=_dc.asdict(llm_cfg),
                engine_kwargs=dict(
                    max_slots=max_slots, page_size=16, max_pages_per_seq=8,
                    steps_per_tick=8, max_tick_steps=8, pipeline_depth=2,
                    ignore_eos=True,
                ),
            ))
            tok = ByteTokenizer(llm_cfg.vocab_size)
            return [ProcessReplica(spec, tok, replica_id=i,
                                   build_timeout_s=600.0)
                    for i in range(n)]
        return [PagedGenerationService(eng) for eng in get_engines(n)]

    # 8 distinct session heads: follow-ups within one session share a
    # prefix, so affinity routing has something real to route on
    sessions = [
        f"session {s:02d} shared conversational context head kept identical "
        f"across this session's turns for prefix reuse measurement"
        for s in range(8)
    ]

    from sentio_tpu.infra.phases import duty_fractions

    def _duty_snapshot(rs) -> list[tuple[dict, float]]:
        """(phase_seconds, duty_elapsed_s) per replica, for level diffs."""
        return [
            (dict(s.get("phase_seconds") or {}), s.get("duty_elapsed_s", 0.0))
            for s in rs.stats()["replicas"]
        ]

    def _duty_delta(before, after) -> list[dict]:
        """Per-replica host/device/idle fractions over the window between
        two snapshots — the per-level time-attribution evidence."""
        out = []
        for (b_phase, b_t), (a_phase, a_t) in zip(before, after):
            deltas = {k: a_phase.get(k, 0.0) - b_phase.get(k, 0.0)
                      for k in a_phase}
            out.append(duty_fractions(deltas, a_t - b_t))
        return out

    def run_level(rs, qps: float, rng: random.Random) -> dict:
        stats = {"arrivals": 0, "ok": 0, "shed": 0, "expired": 0, "error": 0}
        e2e: list[float] = []
        ttft: list[float] = []
        tpot: list[float] = []
        lock = threading.Lock()
        duty_before = _duty_snapshot(rs)

        def gen_worker(prompt: str) -> None:
            t0 = time.perf_counter()
            try:
                r = rs.generate(prompt, max_new_tokens=gen_tokens,
                                temperature=0.0, timeout_s=180)
                dt_ms = (time.perf_counter() - t0) * 1e3
                with lock:
                    if r.finish_reason == "error":
                        stats["error"] += 1
                    else:
                        stats["ok"] += 1
                        e2e.append(dt_ms)
            except ServiceOverloaded:
                with lock:
                    stats["shed"] += 1
            except DeadlineExceededError:
                with lock:
                    stats["expired"] += 1
            except Exception:  # noqa: BLE001 — harness: count, don't die
                with lock:
                    stats["error"] += 1

        def stream_worker(prompt: str) -> None:
            t0 = time.perf_counter()
            t_first = first_chars = chars = 0.0
            try:
                for piece in rs.generate_stream(
                    prompt, max_new_tokens=gen_tokens, temperature=0.0,
                    timeout_s=180,
                ):
                    if not t_first:
                        t_first = time.perf_counter() - t0
                        first_chars = len(piece)
                    chars += len(piece)
                dt = time.perf_counter() - t0
                with lock:
                    stats["ok"] += 1
                    e2e.append(dt * 1e3)
                    if t_first:
                        ttft.append(t_first * 1e3)
                        tail = chars - first_chars
                        if tail > 0 and dt > t_first:
                            # byte tokenizer: chars == tokens exactly; for
                            # BPE this is an upper bound on token count
                            tpot.append((dt - t_first) / tail * 1e3)
            except ServiceOverloaded:
                with lock:
                    stats["shed"] += 1
            except DeadlineExceededError:
                with lock:
                    stats["expired"] += 1
            except Exception:  # noqa: BLE001
                with lock:
                    stats["error"] += 1

        threads: list[threading.Thread] = []
        t_start = time.perf_counter()
        stop_t = t_start + level_s
        seq = 0
        while time.perf_counter() < stop_t:
            session = rng.choice(sessions)
            prompt = f"{session} turn {seq}"
            worker = stream_worker if rng.random() < stream_frac else gen_worker
            t = threading.Thread(target=worker, args=(prompt,), daemon=True)
            t.start()
            threads.append(t)
            stats["arrivals"] += 1
            seq += 1
            time.sleep(rng.expovariate(qps))
        for t in threads:
            t.join(timeout=240)
        wall = time.perf_counter() - t_start
        hung = sum(t.is_alive() for t in threads)
        out = {
            "offered_qps": qps,
            "arrivals": stats["arrivals"],
            "completed": stats["ok"],
            "achieved_qps": round(stats["ok"] / max(wall, 1e-9), 2),
            "shed": stats["shed"],
            "expired": stats["expired"],
            "errors": stats["error"] + hung,
            "shed_rate": round(stats["shed"] / max(stats["arrivals"], 1), 4),
            "wall_s": round(wall, 2),
            # per-replica host/device/idle over THIS level's window: how
            # much of each pump's wall time was GIL-holding host work vs
            # blocked-on-device vs idle (infra/phases.py)
            "duty_cycle_per_replica": _duty_delta(
                duty_before, _duty_snapshot(rs)),
        }
        for label, vals in (("e2e_ms", e2e), ("ttft_ms", ttft),
                            ("tpot_ms", tpot)):
            if vals:
                out[label] = {
                    "p50": round(_percentile(vals, 0.50), 2),
                    "p95": round(_percentile(vals, 0.95), 2),
                    "p99": round(_percentile(vals, 0.99), 2),
                    "n": len(vals),
                }
        return out

    def run_mode(mode: str) -> dict:
        out: dict = {"by_replicas": {}}
        sustained: dict[int, float] = {}
        duty_by_count: dict[int, list[dict]] = {}
        for n in replica_counts:
            log(f"phase LOAD[{mode}]: building {n}-replica set ...")
            svcs = build_replicas(mode, n)
            rs = ReplicaSet(svcs)
            log(f"phase LOAD[{mode}]: warmup ({n} replicas) ...")
            t0 = time.perf_counter()
            warm = rs.warmup(max_new_tokens=gen_tokens)
            log(f"  warmup: {warm['prompts']} prompts, "
                f"{warm['xla_compiles']} compiles in "
                f"{time.perf_counter() - t0:.1f}s")
            get_flight_recorder().clear()
            set_metrics(MetricsCollector())  # per-count isolation
            for svc in svcs:
                # ladder duty windows must exclude warmup's
                # compile-dominated ticks, which would swamp the host
                # fraction (process mode: an RPC re-bases the worker's)
                svc.reset_duty_cycle()
            curve = []
            sustained_n = 0.0
            for qps in qps_ladder:
                level = run_level(rs, qps, random.Random(seed))
                curve.append(level)
                log(f"phase LOAD[{mode}]: replicas={n} offered={qps} "
                    f"achieved={level['achieved_qps']} "
                    f"shed_rate={level['shed_rate']} "
                    f"e2e_p50={level.get('e2e_ms', {}).get('p50')}ms")
                if level["shed_rate"] <= shed_slo and level["errors"] == 0:
                    sustained_n = max(sustained_n, level["achieved_qps"])
            # two-turn session probe: affinity measured END TO END — the
            # second turn must land on the replica holding turn one's KV
            # and actually reuse it
            probe_head = ("affinity probe session head long enough to span "
                          "multiple sixteen token cache pages comfortably")
            rs.generate(probe_head + " turn one", max_new_tokens=4,
                        temperature=0.0, timeout_s=180)
            hits_before = [s.get("prefix_hit_tokens", 0)
                           for s in rs.stats()["replicas"]]
            second = rs.generate(probe_head + " turn two", max_new_tokens=4,
                                 temperature=0.0, timeout_s=180)
            set_stats = rs.stats()
            # the replica whose hit counter MOVED between the probe's turns
            # is the one that actually served turn two (cumulative argmax
            # would attribute the probe to whichever replica served the
            # most load-phase session follow-ups)
            probe_deltas = [
                s.get("prefix_hit_tokens", 0) - hits_before[i]
                for i, s in enumerate(set_stats["replicas"])
            ]
            # whole-ladder duty per replica (warmup excluded via the
            # reset): in thread mode the host fraction here, times N, is
            # the single-process GIL load; in process mode each fraction
            # is measured inside its own worker process
            ladder_duty = [svc.duty_cycle() for svc in svcs]
            duty_by_count[n] = ladder_duty
            out["by_replicas"][str(n)] = {
                "levels": curve,
                "sustained_qps_at_slo": sustained_n,
                "routing": set_stats["routing"],
                "duty_cycle_per_replica": ladder_duty,
                "per_replica_prefix_hit_token_ratio": [
                    s.get("prefix_hit_token_ratio", 0.0)
                    for s in set_stats["replicas"]
                ],
                "affinity_probe": {
                    "second_turn_prefix_hit_tokens":
                        second.prefix_hit_tokens,
                    "routed_replica": max(range(n),
                                          key=lambda i: probe_deltas[i]),
                },
            }
            sustained[n] = sustained_n
            rs.close()
        if len(sustained) > 1:
            lo, hi = min(sustained), max(sustained)
            if sustained[lo] > 0:
                out["throughput_ratio"] = {
                    "replicas": [lo, hi],
                    "sustained_qps": [sustained[lo], sustained[hi]],
                    "ratio": round(sustained[hi] / sustained[lo], 3),
                }
        if duty_by_count:
            # THE GIL probe (ROADMAP item 1): per-replica host fraction at
            # each replica count, next to the measured scaling ratio. In
            # thread mode all N pumps share one Python process — summed
            # host fraction approaching 1 is the quantified ceiling; in
            # process mode each replica owns a GIL, so the honest signal
            # is the PER-REPLICA fraction staying flat (and the scaling
            # ratio climbing) as replicas are added.
            out["gil_probe"] = {
                "replica_mode": mode,
                "host_fraction_by_replicas": {
                    str(n): [round(d["host"], 4) for d in duties]
                    for n, duties in duty_by_count.items()
                },
                "host_fraction_sum_by_replicas": {
                    str(n): round(sum(d["host"] for d in duties), 4)
                    for n, duties in duty_by_count.items()
                },
                **({"scaling_ratio": out["throughput_ratio"]["ratio"]}
                   if "throughput_ratio" in out else {}),
                "note": ("thread: summed host fraction ~1.0 means the "
                         "pumps saturate one GIL; process: fractions are "
                         "per-worker-process, one GIL each"),
            }
        log(f"phase LOAD[{mode}]: sustained {sustained}")
        return out

    result: dict = {
        "knobs": {
            "replica_counts": replica_counts, "qps_ladder": qps_ladder,
            "level_s": level_s, "slots_per_replica": max_slots,
            "gen_tokens": gen_tokens, "stream_frac": stream_frac,
            "shed_slo": shed_slo, "seed": seed,
            "replica_modes": replica_modes,
        },
    }
    by_mode = {mode: run_mode(mode) for mode in replica_modes}
    # legacy top-level shape: the first (usually thread) mode's results
    primary = by_mode.get("thread") or next(iter(by_mode.values()))
    result.update(primary)
    if len(by_mode) > 1:
        result["by_mode"] = by_mode
        # the mode comparison the artifact leads with: same ladder, same
        # replica counts, thread vs process — scaling ratio and host
        # fractions side by side
        result["gil_probe_per_mode"] = {
            mode: out.get("gil_probe") for mode, out in by_mode.items()
        }
    set_metrics(MetricsCollector())  # leave a clean collector behind
    return result


def phase_chaos(llm_cfg, new_tokens, replica_mode=None, chaos_mode=None):
    """Replica chaos drill over the open-loop harness (BENCH_CHAOS=1):
    a 2-replica set serves a steady Poisson arrival stream; mid-run one
    replica suffers the scenario picked by ``BENCH_CHAOS_MODE``:

    * ``kill`` (default) — the next decode tick raises AND its
      ``engine.reset()`` is forced to fail: the replica latches broken and
      the supervisor rebuilds it in place from the shared weights;
    * ``stall`` — the next decode tick WEDGES (stall fault: blocks,
      raising nothing) exactly like a hung device dispatch; nothing
      latches, so recovery rests entirely on the pump-heartbeat watchdog:
      quarantine on heartbeat age, inbox handoff to the survivor, engine
      abandonment, in-place rebuild;
    * ``midstream`` — half the traffic is SSE-shaped streams and the
      replica dies while streams are MID-DELIVERY (thread mode: tick
      fault + reset denied; process mode: a real ``SIGKILL`` armed at the
      ``worker.stream_chunk`` point, between delivered chunks). Delivered
      -token streams must RESUME by replay-prefill on the survivor; the
      artifact records ``resumed_streams``, ``replayed_tokens_total``,
      ``splice_exact`` (every resumed stream byte-identical to its
      no-fault greedy reference) and ``non_resumable_errors`` (target 0
      within budget).
    * ``partition`` (socket replicas only) — a HALF-OPEN network
      partition instead of a death: the router's reads from the victim
      stall (no EOF, no error, worker alive and decoding) while writes
      still land, mid-delivery like the midstream drill. Detection rests
      entirely on status-frame staleness (transport-liveness contract);
      recovery is re-registration at a higher incarnation epoch, and
      every pre-partition frame is dropped by the epoch fence. The
      artifact's midstream fields apply, plus ``stale_frames_dropped``,
      ``heal_vs_respawn`` (did the live worker keep its process?), and
      the victim's post-incident ``incarnation``.

    The artifact answers the operator questions: **availability**
    (completed / arrivals — the error-budget fraction is its complement),
    **p95 during the incident window** (requests arriving between the kill
    and the set reporting all-HEALTHY again), **time-to-recover** (kill →
    rebuilt replica back in rotation), **detection latency** (kill → first
    replica out of HEALTHY — for stalls this is the watchdog's whole
    value), and **handed_off_tickets** (inbox tickets moved to survivors
    at quarantine instead of riding caller failover). Untyped errors are
    counted separately and should be zero.

    ``BENCH_CHAOS_REPLICA_MODE=process`` runs the drill against
    PROCESS-mode replicas (runtime/worker.py): ``kill`` becomes a real
    mid-dispatch ``SIGKILL`` of the victim's worker process (armed inside
    the worker via the RPC fault surface — no Python frame unwinds, the
    supervisor must find the corpse from the outside and RESPAWN it), and
    ``stall`` wedges the worker's pump with an in-worker stall fault
    (recovery reaps the whole wedged process instead of abandoning a
    thread).

    Env knobs: BENCH_CHAOS_QPS (8), BENCH_CHAOS_SECONDS (30),
    BENCH_CHAOS_KILL_AT_S (5), BENCH_CHAOS_SLOTS (8),
    BENCH_CHAOS_SEED (1234), BENCH_CHAOS_MODE
    (kill|stall|midstream|elastic — ``elastic`` dispatches to
    :func:`phase_elastic`, membership churn instead of a replica death),
    BENCH_CHAOS_STALL_BUDGET_S (2), BENCH_CHAOS_REPLICA_MODE
    (thread|process, or a comma list — the caller runs this phase once
    per listed mode from one invocation)."""
    import random
    import threading

    from sentio_tpu.infra import faults
    from sentio_tpu.infra.exceptions import (
        DeadlineExceededError,
        SentioError,
        ServiceOverloaded,
    )
    from sentio_tpu.infra.metrics import MetricsCollector, set_metrics
    from sentio_tpu.runtime.paged import ContinuousBatchingEngine
    from sentio_tpu.runtime.replica import ReplicaSet
    from sentio_tpu.runtime.service import PagedGenerationService

    from sentio_tpu.infra.metrics import get_metrics

    qps = float(os.environ.get("BENCH_CHAOS_QPS", "8"))
    run_s = float(os.environ.get("BENCH_CHAOS_SECONDS", "30"))
    kill_at_s = float(os.environ.get("BENCH_CHAOS_KILL_AT_S", "5"))
    max_slots = int(os.environ.get("BENCH_CHAOS_SLOTS", "8"))
    seed = int(os.environ.get("BENCH_CHAOS_SEED", "1234"))
    mode = (chaos_mode
            or os.environ.get("BENCH_CHAOS_MODE", "kill")).strip().lower()
    stall_budget_s = float(os.environ.get("BENCH_CHAOS_STALL_BUDGET_S", "2"))
    if replica_mode is None:
        replica_mode = os.environ.get(
            "BENCH_CHAOS_REPLICA_MODE", "thread").strip().lower()
    if mode == "elastic":
        # membership churn IS the fault here — no replica dies, the fleet
        # grows/flaps/shrinks under load (dedicated harness below)
        return phase_elastic(llm_cfg, new_tokens)
    if mode == "partition" and replica_mode != "socket":
        return {"skipped": "partition chaos needs the socket transport "
                           f"(replica_mode={replica_mode})",
                "mode": mode, "replica_mode": replica_mode}
    # partition traffic IS the midstream shape (all streams, several
    # delivered chunks each) — only the armed fault differs
    streamy = mode in ("midstream", "partition")
    gen_tokens = min(new_tokens, 16)
    rng = random.Random(seed)

    log(f"phase CHAOS: building 2-replica set (mode={mode}, "
        f"replica_mode={replica_mode}) ...")
    # stall mode rests on the watchdog: the per-service stall budget must
    # exceed the slowest legitimate tick (warmup has pre-compiled, so the
    # default 2s is generous) but stay small next to the run window
    svc_kw = ({"tick_stall_budget_s": stall_budget_s}
              if mode == "stall" else {})
    # midstream/partition run smaller ticks so every stream spans SEVERAL
    # delivered chunks — at 8-step ticks an 8-token answer ships in one
    # harvest and the fault can never land "between chunks" of a stream
    tick_steps = 4 if streamy else 8
    engine_kw = dict(max_slots=max_slots, page_size=16, max_pages_per_seq=8,
                     steps_per_tick=tick_steps, max_tick_steps=tick_steps,
                     pipeline_depth=2, ignore_eos=True)
    registry = None
    if replica_mode in ("process", "socket"):
        import dataclasses as _dc

        from sentio_tpu.models.tokenizer import ByteTokenizer
        from sentio_tpu.runtime.worker import ProcessReplica, WorkerSpec

        spec_kw = dict(factory_kwargs=dict(
            model_config=_dc.asdict(llm_cfg),
            engine_kwargs=engine_kw,
            service_kwargs=dict(svc_kw),
        ))
        transport_kw = {}
        if replica_mode == "socket":
            from sentio_tpu.runtime.replica import WorkerRegistry

            registry = WorkerRegistry("bench-chaos", slots=2)
            spec_kw.update(auth_token="bench-chaos", status_interval_s=0.05,
                           reconnect=True, reconnect_backoff_s=0.2,
                           router_silence_timeout_s=0.8)
            transport_kw = dict(transport_mode="socket", registry=registry,
                                partition_timeout_s=1.0, ping_interval_s=0.2,
                                heal_grace_s=15.0)
        spec = WorkerSpec(**spec_kw)
        tok = ByteTokenizer(llm_cfg.vocab_size)
        replicas = [ProcessReplica(spec, tok, replica_id=i,
                                   build_timeout_s=600.0, **transport_kw)
                    for i in range(2)]
    else:
        e0 = ContinuousBatchingEngine(model_config=llm_cfg, **engine_kw)
        e1 = ContinuousBatchingEngine(
            model_config=llm_cfg, params=e0.params, tokenizer=e0.tokenizer,
            **engine_kw,
        )
        replicas = [PagedGenerationService(e0, **svc_kw),
                    PagedGenerationService(e1, **svc_kw)]
    rs = ReplicaSet(
        replicas,
        # fast supervision: the drill measures recovery, not poll cadence
        probe_interval_s=0.05, quarantine_backoff_s=0.25,
        breaker_tick_failures=2, failover_budget=2,
        rebuild_drain_s=1.0,
    )
    log("phase CHAOS: warmup ...")
    rs.warmup(max_new_tokens=gen_tokens)
    # midstream: per-prompt no-fault GREEDY references, computed before
    # the incident — a resumed stream's spliced output must be
    # byte-identical to the run that never saw a fault (splice_exact).
    # Stream answers run LONGER than the generate traffic (several
    # delivered chunks at the shrunken midstream tick) so streams spend
    # most of their life mid-delivery — the window the kill must land in
    stream_tokens = max(gen_tokens, 16) if streamy else gen_tokens
    stream_prompts = [f"midstream chaos session {i:02d} steady turn"
                      for i in range(8)]
    expected_text: dict = {}
    victim_pid = victim_epoch = None
    if replica_mode == "socket":
        victim_pid = replicas[1].pid
        victim_epoch = replicas[1].epoch
    if streamy:
        # references run directly on the designated VICTIM (replica 1 —
        # the one the process-mode SIGKILL arms in): its radix then holds
        # every stream prompt's full prefix, so prefix affinity routes
        # every drill stream onto the replica that will die, and the kill
        # provably lands on a pump with live delivered streams instead of
        # the idle sibling's (seeded replica inits are identical, so the
        # reference text is valid for whichever replica resumes it)
        for p in stream_prompts:
            expected_text[p] = replicas[1].generate(
                p, max_new_tokens=stream_tokens, temperature=0.0,
                timeout_s=180).text
    set_metrics(MetricsCollector())

    lock = threading.Lock()
    stats = {"arrivals": 0, "ok": 0, "shed": 0, "expired": 0,
             "typed_errors": 0, "untyped_errors": 0}
    # midstream bookkeeping: resumed-stream splice checks + streams that
    # delivered tokens and STILL surfaced the typed mid-stream error
    mid = {"streams": 0, "splice_checked": 0, "splice_mismatch": 0,
           "non_resumable_errors": 0}
    # count of streams that have delivered ≥1 chunk and are still
    # mid-delivery RIGHT NOW: the thread-mode kill arms only while this
    # is non-zero, so the tick fault provably lands on a replica set with
    # live delivered streams (process mode needs no gate — its
    # worker.stream_chunk injection point IS between delivered chunks)
    live_delivered = [0]  # guarded-by: lock
    # (arrival time relative to t_start, e2e latency ms) for completions
    completions: list[tuple[float, float]] = []
    t_state = {"kill": None, "detect": None, "recover": None, "done": False}
    # telemetry-plane-under-fire bookkeeping (process/socket): worst
    # telemetry age observed inside the incident window (the observability
    # gap the outage opened) and the worst clock-offset uncertainty bound
    tel = {"gap_max_s": None, "offset_bound_max_s": None}
    stall_release = threading.Event()
    partition_release = threading.Event()

    def worker(prompt: str, t_rel: float) -> None:
        t0 = time.perf_counter()
        try:
            r = rs.generate(prompt, max_new_tokens=gen_tokens,
                            temperature=0.0, timeout_s=180)
            dt_ms = (time.perf_counter() - t0) * 1e3
            with lock:
                if r.finish_reason == "error":
                    stats["typed_errors"] += 1
                else:
                    stats["ok"] += 1
                    completions.append((t_rel, dt_ms))
        except ServiceOverloaded:
            with lock:
                stats["shed"] += 1
        except DeadlineExceededError:
            with lock:
                stats["expired"] += 1
        except SentioError:
            with lock:
                stats["typed_errors"] += 1
        except Exception:  # noqa: BLE001 — the number that must stay zero
            with lock:
                stats["untyped_errors"] += 1

    def stream_worker(prompt: str, t_rel: float) -> None:
        t0 = time.perf_counter()
        so: dict = {}
        pieces: list = []
        try:
            try:
                for piece in rs.generate_stream(
                        prompt, max_new_tokens=stream_tokens,
                        temperature=0.0, timeout_s=180, stats_out=so):
                    pieces.append(piece)
                    if len(pieces) == 1:
                        with lock:
                            live_delivered[0] += 1
                dt_ms = (time.perf_counter() - t0) * 1e3
                with lock:
                    stats["ok"] += 1
                    completions.append((t_rel, dt_ms))
                    if so.get("resumed"):
                        mid["splice_checked"] += 1
                        if "".join(pieces) != expected_text.get(prompt):
                            mid["splice_mismatch"] += 1
            finally:
                # the kill-arming gate reads this: EVERY exit path of a
                # stream that delivered (incl. a resume re-admission shed
                # AFTER chunks were out) must unwind its live increment
                if pieces:
                    with lock:
                        live_delivered[0] -= 1
        except ServiceOverloaded:
            with lock:
                stats["shed"] += 1
        except DeadlineExceededError:
            with lock:
                stats["expired"] += 1
        except SentioError:
            with lock:
                stats["typed_errors"] += 1
                # delivered tokens AND a typed mid-stream error: the
                # resume machinery did not save this stream
                if pieces:
                    mid["non_resumable_errors"] += 1
        except Exception:  # noqa: BLE001 — must stay zero
            with lock:
                stats["untyped_errors"] += 1

    def watcher(t_start: float) -> None:
        # detection clock: kill → first replica out of HEALTHY (for stalls
        # this measures the watchdog, the headline of the scenario);
        # recovery clock: kill → the set reports all-HEALTHY again.
        # Recovery only counts AFTER detection — a stall leaves the set
        # reporting healthy for a full watchdog budget after the wedge,
        # and "recovered before anything was detected" is not recovery
        while t_state["recover"] is None and not t_state["done"]:
            if t_state["kill"] is not None:
                summary = rs.health_summary()
                if t_state["detect"] is None and any(
                        r["state"] != "HEALTHY"
                        for r in summary["replicas"]):
                    t_state["detect"] = time.perf_counter() - t_start
                if t_state["detect"] is not None and \
                        summary["status"] == "healthy":
                    t_state["recover"] = time.perf_counter() - t_start
                    return
            time.sleep(0.02)

    def telemetry_watcher() -> None:
        # the telemetry plane under fire: sample the VICTIM's telemetry
        # age and clock bound through the drill — always re-reading
        # rs._services[1], because heal/respawn replaces the shim object —
        # and keep the worst gap seen inside the incident window
        while not t_state["done"]:
            svc = rs._services[1]
            age_fn = getattr(svc, "telemetry_age", None)
            if callable(age_fn):
                try:
                    age = age_fn()
                except Exception:  # noqa: BLE001 — shim mid-replacement
                    age = None
                if age is not None and t_state["kill"] is not None:
                    if tel["gap_max_s"] is None or age > tel["gap_max_s"]:
                        tel["gap_max_s"] = age
            clock_fn = getattr(svc, "clock_sync", None)
            if callable(clock_fn):
                est = clock_fn()
                if est is not None and (
                        tel["offset_bound_max_s"] is None
                        or est["uncertainty_s"] > tel["offset_bound_max_s"]):
                    tel["offset_bound_max_s"] = est["uncertainty_s"]
            time.sleep(0.05)

    threads: list[threading.Thread] = []
    t_start = time.perf_counter()
    w = threading.Thread(target=watcher, args=(t_start,), daemon=True)
    w.start()
    if replica_mode in ("process", "socket"):
        threading.Thread(target=telemetry_watcher, daemon=True).start()
    killed = False
    seq = 0
    while time.perf_counter() - t_start < run_s:
        t_rel = time.perf_counter() - t_start
        # thread-mode midstream holds its fire until a stream is provably
        # mid-delivery (≥1 chunk out, not finished): a tick fault armed
        # into an idle-stream window would drill plain failover, not
        # resume-by-replay. Process mode needs no gate — the SIGKILL arms
        # at worker.stream_chunk, BETWEEN delivered chunks by definition.
        if streamy and not (mode == "midstream"
                            and replica_mode == "process"):
            with lock:
                midstream_ready = live_delivered[0] > 0
        else:
            midstream_ready = True
        if not killed and t_rel >= kill_at_s and midstream_ready:
            if mode == "partition":
                # half-open partition of the victim: the router's reads
                # from replica 1 wedge (frames buffer unread) while its
                # writes — and the worker itself — stay fully alive
                faults.arm("transport.recv.r1", faults.FaultRule(
                    stall_event=partition_release,
                    stall_s=run_s + 300.0, times=1))
            elif replica_mode in ("process", "socket"):
                # the fault arms INSIDE the victim's worker process via
                # the RPC fault surface: its next decode tick either takes
                # a REAL mid-dispatch SIGKILL (no handler, no unwinding —
                # the supervisor must detect the corpse from the outside
                # and respawn the process) or wedges in-worker
                victim = replicas[1]
                if mode == "stall":
                    victim.inject_fault("paged.step",
                                        stall_s=run_s + 300.0, times=1)
                elif mode == "midstream":
                    # a real SIGKILL BETWEEN delivered stream chunks: the
                    # victim dies exactly while a stream is mid-delivery,
                    # the case only resume-by-replay can save
                    victim.inject_fault("worker.stream_chunk",
                                        kill_process=True, times=1)
                else:
                    victim.inject_fault("paged.step", kill_process=True,
                                        times=1)
            elif mode == "stall":
                # one-shot wedge: the next decode tick anywhere BLOCKS
                # (raising nothing) until released after the run — the
                # watchdog must find it by heartbeat age alone
                faults.arm("paged.step", faults.FaultRule(
                    stall_event=stall_release,
                    stall_s=run_s + 300.0, times=1))
            else:
                # one-shot kill: the next decode tick anywhere fails, and
                # that pump's recovery reset fails too → latched broken
                faults.arm("paged.step", faults.FaultRule(
                    error=RuntimeError("bench chaos: replica kill"),
                    times=1))
                faults.arm("engine.reset", faults.FaultRule(
                    error=RuntimeError("bench chaos: reset denied"),
                    times=1))
            t_state["kill"] = t_rel
            killed = True
            log(f"phase CHAOS: replica {mode} armed at t={t_rel:.1f}s "
                f"({replica_mode})")
        if streamy:
            # the midstream/partition drills' offered traffic is ALL
            # SSE-shaped streams (the generate path is what the
            # kill/stall modes drill): combined with victim-side
            # reference warming above, the one-shot fault lands on a
            # pump with live delivered streams to splice
            sp = stream_prompts[seq % len(stream_prompts)]
            with lock:
                mid["streams"] += 1
            t = threading.Thread(target=stream_worker, args=(sp, t_rel),
                                 daemon=True)
        else:
            prompt = f"chaos session {seq % 8:02d} steady traffic turn {seq}"
            t = threading.Thread(target=worker, args=(prompt, t_rel),
                                 daemon=True)
        t.start()
        threads.append(t)
        with lock:
            stats["arrivals"] += 1
        seq += 1
        time.sleep(rng.expovariate(qps))
    for t in threads:
        t.join(timeout=240)
    hung = sum(t.is_alive() for t in threads)
    # recovery may land after the last arrival; give the supervisor a
    # bounded grace to finish the rebuild before declaring non-recovery —
    # but ONLY if a kill actually happened (kill_at_s past the run window
    # means there is no incident to recover from)
    if killed:
        grace_end = time.perf_counter() + 120
        while t_state["recover"] is None and time.perf_counter() < grace_end:
            time.sleep(0.1)
    t_state["done"] = True  # stop the watcher (it idles if never killed)
    stall_release.set()  # unwedge the abandoned pump so it can exit
    # heal the partition AFTER recovery: the old connection's buffered
    # pre-partition frames drain straight into the stale-epoch fence
    partition_release.set()
    faults.reset()

    t_kill = t_state["kill"]
    t_detect = t_state["detect"]
    t_recover = t_state["recover"]
    incident = [lat for (t_rel, lat) in completions
                if t_kill is not None
                and t_kill <= t_rel <= (t_recover if t_recover is not None
                                        else float("inf"))]
    steady = [lat for (t_rel, lat) in completions
              if t_kill is None or t_rel < t_kill]
    arrivals = max(stats["arrivals"], 1)
    set_stats = rs.stats()
    out = {
        "knobs": {"qps": qps, "run_s": run_s, "kill_at_s": kill_at_s,
                  "slots_per_replica": max_slots, "gen_tokens": gen_tokens,
                  "seed": seed, "mode": mode,
                  "replica_mode": replica_mode,
                  **({"stall_budget_s": stall_budget_s}
                     if mode == "stall" else {}),
                  **({"stream_tokens": stream_tokens}
                     if mode == "midstream" else {})},
        **stats,
        "hung": hung,
        # the headline: fraction of offered requests that completed — its
        # complement is the error budget the incident consumed
        "availability": round(stats["ok"] / arrivals, 4),
        "killed": killed,
        # kill → first replica out of HEALTHY: for the stall scenario this
        # is pure watchdog latency (nothing raised); for kill it is the
        # caller-path breaker's reaction time
        "detection_latency_s": (round(t_detect - t_kill, 2)
                                if t_detect is not None and t_kill is not None
                                else None),
        "time_to_recover_s": (round(t_recover - t_kill, 2)
                              if t_recover is not None and t_kill is not None
                              else None),
        # None (not False) when no kill was armed: there was no incident
        "recovered": (t_recover is not None) if killed else None,
        "detected": (t_detect is not None) if killed else None,
        "health": rs.health_summary(),
        "failovers": set_stats.get("failovers", 0),
        # quarantine inbox handoff: tickets that completed on a survivor
        # WITHOUT consuming their callers' failover budget
        "handed_off_tickets": set_stats.get("handed_off", 0),
        "stall_quarantines": set_stats.get("stall_quarantines", 0),
    }
    if streamy:
        # resumable-stream telemetry: every delivered-token stream the
        # incident touched should RESUME (non_resumable_errors == 0 within
        # budget) and every resumed completion should be byte-identical to
        # its no-fault greedy reference (splice_exact)
        out["streams_offered"] = mid["streams"]
        out["resumed_streams"] = set_stats.get("stream_resumes", 0)
        out["replayed_tokens_total"] = set_stats.get(
            "resume_replayed_tokens", 0)
        out["resume_exhausted"] = set_stats.get("resume_exhausted", 0)
        out["non_resumable_errors"] = mid["non_resumable_errors"]
        out["resumed_completions_checked"] = mid["splice_checked"]
        out["splice_exact"] = (mid["splice_mismatch"] == 0
                               if mid["splice_checked"] else None)
    if mode == "partition" and registry is not None:
        # the epoch fence at work: give the released (previously wedged)
        # old connection a moment to drain its buffered pre-partition
        # frames, then record how many the fence dropped, whether the
        # live worker HEALED (kept its process across re-registration)
        # or had to be respawned, and the victim's final incarnation
        drain_end = time.perf_counter() + 15
        while registry.stale_frames(1) == 0 and \
                time.perf_counter() < drain_end:
            time.sleep(0.1)
        cur = rs._services[1]
        out["stale_frames_dropped"] = registry.stale_frames(1)
        out["heal_vs_respawn"] = (
            ("heal" if cur.pid == victim_pid else "respawn")
            if killed and t_recover is not None else None)
        out["incarnation"] = cur.epoch
        out["incarnation_before"] = victim_epoch
    if replica_mode in ("process", "socket"):
        # the observability plane's own incident report: how long the
        # fleet flew blind (worst telemetry age inside the incident
        # window), how many stale-epoch deltas the merge fence refused
        # (double-count protection at work), and the worst clock-offset
        # uncertainty bound the trace re-basing had to wear
        stale_dropped = sum(
            v for k, v in get_metrics().memory.counters.items()
            if k.startswith("worker_telemetry_dropped")
            and "stale_epoch" in k)
        out["telemetry"] = {
            "gap_max_s": (round(tel["gap_max_s"], 3)
                          if tel["gap_max_s"] is not None else None),
            "stale_deltas_dropped": int(stale_dropped),
            "clock_offset_bound_max_s": (
                round(tel["offset_bound_max_s"], 6)
                if tel["offset_bound_max_s"] is not None else None),
        }
    if steady:
        out["steady_p95_ms"] = round(_percentile(steady, 0.95), 2)
    if incident:
        out["incident_p95_ms"] = round(_percentile(incident, 0.95), 2)
        out["incident_completions"] = len(incident)
    rs.close()
    # let the released (previously wedged) pump unwind before returning:
    # it exits at its next loop top now that its service is closed, and a
    # pump still inside XLA at interpreter exit aborts the process
    unwind_end = time.perf_counter() + 30
    while time.perf_counter() < unwind_end and any(
            t.name == "paged-decode-pump" and t.is_alive()
            for t in threading.enumerate()):
        time.sleep(0.05)
    if registry is not None:
        registry.close()
    if replica_mode in ("process", "socket"):
        # acceptance telemetry: close() must have REAPED every worker
        # (SIGKILLed, wedged, partitioned-then-healed, and respawned
        # alike) — orphan_workers != 0 in the artifact is a failed drill
        import multiprocessing

        reap_end = time.perf_counter() + 30
        while time.perf_counter() < reap_end and \
                multiprocessing.active_children():
            time.sleep(0.05)
        out["orphan_workers"] = len(multiprocessing.active_children())
    set_metrics(MetricsCollector())
    extra = ""
    if streamy:
        extra = (f" resumed={out['resumed_streams']} "
                 f"replayed={out['replayed_tokens_total']} "
                 f"splice_exact={out['splice_exact']} "
                 f"non_resumable={out['non_resumable_errors']}")
    if mode == "partition":
        extra += (f" stale_dropped={out.get('stale_frames_dropped')} "
                  f"outcome={out.get('heal_vs_respawn')} "
                  f"epoch={out.get('incarnation')}")
    log(f"phase CHAOS[{mode}/{replica_mode}]: "
        f"availability={out['availability']} "
        f"detect={out['detection_latency_s']}s "
        f"ttr={out['time_to_recover_s']}s "
        f"incident_p95={out.get('incident_p95_ms')}ms "
        f"handed_off={out['handed_off_tickets']} "
        f"untyped={stats['untyped_errors']}{extra}")
    return out


def phase_elastic(llm_cfg, new_tokens):
    """Elastic-fleet churn drill (``BENCH_CHAOS_MODE=elastic``): a steady
    Poisson mix of generate + SSE-shaped stream traffic rides a fleet
    whose MEMBERSHIP is the fault — a mid-run join storm grows 1→N, a
    flap cycle joins/retires the same slot back to back, and a scale-in
    wave retires every extra replica while streams are mid-delivery
    (graceful drain: delivered-token streams finish or resume, queued
    tickets hand off to survivors). A live duty-cycle autoscaler
    (runtime/autoscaler.py) polls the whole time with aggressive
    thresholds, so the artifact also records the closed loop's own
    decisions racing the scripted churn.

    The artifact answers: **availability** under churn (its complement is
    the error budget membership changes consumed), **retire drain p95**
    (the latency bill of a graceful scale-in), **handed_off_tickets**
    (queued work moved to survivors instead of riding caller failover),
    **autoscale decisions** by direction, and **untyped_errors** — which
    must be ZERO: churn is a planned operation, every caller-visible
    outcome stays typed.

    Env knobs: BENCH_CHAOS_QPS (8), BENCH_CHAOS_SECONDS (30),
    BENCH_CHAOS_SLOTS (8), BENCH_CHAOS_SEED (1234),
    BENCH_ELASTIC_MAX_REPLICAS (3)."""
    import random
    import threading

    from sentio_tpu.infra.exceptions import (
        DeadlineExceededError,
        SentioError,
        ServiceOverloaded,
    )
    from sentio_tpu.infra.metrics import (
        MetricsCollector,
        get_metrics,
        set_metrics,
    )
    from sentio_tpu.runtime.autoscaler import AutoscalePolicy, Autoscaler
    from sentio_tpu.runtime.paged import ContinuousBatchingEngine
    from sentio_tpu.runtime.replica import ReplicaSet
    from sentio_tpu.runtime.service import PagedGenerationService

    qps = float(os.environ.get("BENCH_CHAOS_QPS", "8"))
    run_s = float(os.environ.get("BENCH_CHAOS_SECONDS", "30"))
    max_slots = int(os.environ.get("BENCH_CHAOS_SLOTS", "8"))
    seed = int(os.environ.get("BENCH_CHAOS_SEED", "1234"))
    max_replicas = max(int(os.environ.get(
        "BENCH_ELASTIC_MAX_REPLICAS", "3")), 2)
    gen_tokens = min(new_tokens, 16)
    rng = random.Random(seed)

    log(f"phase ELASTIC: building 1-replica seed fleet "
        f"(max={max_replicas}) ...")
    engine_kw = dict(max_slots=max_slots, page_size=16, max_pages_per_seq=8,
                     steps_per_tick=4, max_tick_steps=4, pipeline_depth=2,
                     ignore_eos=True)
    e0 = ContinuousBatchingEngine(model_config=llm_cfg, **engine_kw)

    def new_service() -> PagedGenerationService:
        eng = ContinuousBatchingEngine(
            model_config=llm_cfg, params=e0.params, tokenizer=e0.tokenizer,
            **engine_kw)
        return PagedGenerationService(eng)

    rs = ReplicaSet(
        [PagedGenerationService(e0)],
        probe_interval_s=0.05, quarantine_backoff_s=0.25,
        failover_budget=2, rebuild_drain_s=5.0,
    )
    log("phase ELASTIC: warmup ...")
    rs.warmup(max_new_tokens=gen_tokens)
    set_metrics(MetricsCollector())

    # the autoscaler runs LIVE through the drill with thresholds low
    # enough that tiny-engine duty under this traffic can trip them — its
    # decisions race the scripted churn below, which is the point
    def launcher() -> None:
        rs.add_replica(new_service())

    scaler = Autoscaler(
        rs,
        AutoscalePolicy(min_replicas=1, max_replicas=max_replicas,
                        window_s=2.0, out_busy=0.3, in_busy=0.1,
                        out_backlog=0.3, out_cooldown_s=2.0,
                        in_cooldown_s=3.0),
        launcher=launcher, poll_interval_s=0.25,
    )
    scaler.start()

    lock = threading.Lock()
    stats = {"arrivals": 0, "ok": 0, "shed": 0, "expired": 0,
             "typed_errors": 0, "untyped_errors": 0}
    churn = {"storm_joins": 0, "flap_cycles": 0, "forced_retires": 0,
             "refused": 0}
    completions: list[float] = []

    def worker(prompt: str) -> None:
        t0 = time.perf_counter()
        try:
            r = rs.generate(prompt, max_new_tokens=gen_tokens,
                            temperature=0.0, timeout_s=180)
            with lock:
                if r.finish_reason == "error":
                    stats["typed_errors"] += 1
                else:
                    stats["ok"] += 1
                    completions.append((time.perf_counter() - t0) * 1e3)
        except ServiceOverloaded:
            with lock:
                stats["shed"] += 1
        except DeadlineExceededError:
            with lock:
                stats["expired"] += 1
        except SentioError:
            with lock:
                stats["typed_errors"] += 1
        except Exception:  # noqa: BLE001 — the number that must stay zero
            with lock:
                stats["untyped_errors"] += 1

    def stream_worker(prompt: str) -> None:
        t0 = time.perf_counter()
        try:
            "".join(rs.generate_stream(prompt, max_new_tokens=gen_tokens,
                                       temperature=0.0, timeout_s=180))
            with lock:
                stats["ok"] += 1
                completions.append((time.perf_counter() - t0) * 1e3)
        except ServiceOverloaded:
            with lock:
                stats["shed"] += 1
        except DeadlineExceededError:
            with lock:
                stats["expired"] += 1
        except SentioError:
            with lock:
                stats["typed_errors"] += 1
        except Exception:  # noqa: BLE001 — must stay zero
            with lock:
                stats["untyped_errors"] += 1

    def _retire(idx: int, deadline_s: float) -> bool:
        # scripted retires race the autoscaler's own scale-ins (and each
        # other): a slot someone else is already retiring reports
        # retired=False, the last-serving guard raises typed — both are
        # refusals, not failures
        try:
            return bool(rs.retire(idx, deadline_s=deadline_s)["retired"])
        except SentioError:
            with lock:
                churn["refused"] += 1
            return False

    def _live_extras() -> list[int]:
        summary = rs.health_summary()
        return [r["replica"] for r in summary["replicas"]
                if r["replica"] != 0
                and r["state"] in ("HEALTHY", "DEGRADED")]

    storm_at = run_s * 0.2
    flap_at = run_s * 0.5
    scale_in_at = run_s * 0.75
    fired = {"storm": False, "flap": False, "scale_in": False}
    threads: list[threading.Thread] = []
    t_start = time.perf_counter()
    seq = 0
    while time.perf_counter() - t_start < run_s:
        t_rel = time.perf_counter() - t_start
        if not fired["storm"] and t_rel >= storm_at:
            # join storm: grow to max back to back under live traffic
            fired["storm"] = True
            while rs.stats()["fleet"]["live_replicas"] < max_replicas:
                rs.add_replica(new_service())
                churn["storm_joins"] += 1
            log(f"phase ELASTIC: join storm done at t={t_rel:.1f}s "
                f"(live={rs.stats()['fleet']['live_replicas']})")
        if not fired["flap"] and t_rel >= flap_at:
            # flap: retire a joiner and immediately re-join its slot
            fired["flap"] = True
            extras = _live_extras()
            if extras and _retire(extras[-1], deadline_s=5.0):
                rs.add_replica(new_service())
                churn["flap_cycles"] += 1
            log(f"phase ELASTIC: flap cycle done at t={t_rel:.1f}s")
        if not fired["scale_in"] and t_rel >= scale_in_at:
            # scale-in wave racing mid-flight streams: graceful drain on
            # every extra replica, survivors absorb handed-off tickets
            fired["scale_in"] = True
            for idx in reversed(_live_extras()):
                if _retire(idx, deadline_s=10.0):
                    churn["forced_retires"] += 1
            log(f"phase ELASTIC: scale-in wave done at t={t_rel:.1f}s "
                f"(live={rs.stats()['fleet']['live_replicas']})")
        prompt = f"elastic churn session {seq % 8:02d} turn {seq}"
        target = stream_worker if seq % 2 else worker
        t = threading.Thread(target=target, args=(prompt,), daemon=True)
        t.start()
        threads.append(t)
        with lock:
            stats["arrivals"] += 1
        seq += 1
        time.sleep(rng.expovariate(qps))
    for t in threads:
        t.join(timeout=240)
    hung = sum(t.is_alive() for t in threads)
    scaler.close()
    set_stats = rs.stats()
    decisions = {
        k: int(v) for k, v in get_metrics().memory.counters.items()
        if k.startswith("autoscale_decisions")
    }
    arrivals = max(stats["arrivals"], 1)
    out = {
        "knobs": {"qps": qps, "run_s": run_s, "slots_per_replica": max_slots,
                  "gen_tokens": gen_tokens, "seed": seed, "mode": "elastic",
                  "max_replicas": max_replicas},
        **stats,
        "hung": hung,
        "availability": round(stats["ok"] / arrivals, 4),
        "churn": churn,
        "fleet": set_stats["fleet"],
        "handed_off_tickets": set_stats.get("handed_off", 0),
        "autoscale": scaler.stats(),
        "autoscale_decisions": decisions,
        "stream_resumes": set_stats.get("stream_resumes", 0),
        "resume_exhausted": set_stats.get("resume_exhausted", 0),
        "pump_leaked": set_stats.get("pump_leaked", 0),
        "health": rs.health_summary(),
    }
    if completions:
        out["e2e_p95_ms"] = round(_percentile(completions, 0.95), 2)
    rs.close()
    # retired engines idle-exit their pumps; a pump still inside XLA at
    # interpreter exit aborts the process
    unwind_end = time.perf_counter() + 30
    while time.perf_counter() < unwind_end and any(
            t.name == "paged-decode-pump" and t.is_alive()
            for t in threading.enumerate()):
        time.sleep(0.05)
    set_metrics(MetricsCollector())
    fleet = out["fleet"]
    log(f"phase ELASTIC: availability={out['availability']} "
        f"joined={fleet['joined']} retired={fleet['retired']} "
        f"drain_p95={fleet.get('retire_drain_p95_s')}s "
        f"handed_off={out['handed_off_tickets']} "
        f"autoscale={out['autoscale']} "
        f"untyped={stats['untyped_errors']}")
    return out


def phase_d_kernels():
    """Kernel-vs-XLA timings on the real chip: flash attention (prefill
    shape) and the paged decode kernel (page-table walk vs gather). Each
    timing wraps the op in jit and measures dispatch→fetch round trips, so
    the delta isolates the kernel."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from sentio_tpu.kernels.flash_attention import flash_attention
    from sentio_tpu.kernels.paged_attention import paged_attention
    from sentio_tpu.models.layers import attention, causal_mask
    from sentio_tpu.runtime.paged import _paged_attn_xla

    on_tpu = jax.default_backend() == "tpu"
    rng = np.random.default_rng(0)

    def timeit(fn, *args, n=8):
        np.asarray(fn(*args))  # compile
        t0 = time.perf_counter()
        for _ in range(n):
            out = fn(*args)
        np.asarray(out)
        return (time.perf_counter() - t0) / n * 1000.0

    out = {}
    # prefill-shaped causal attention: B4 T2048 H8 D64 bf16
    b, t, h, d = 4, 2048, 8, 64
    q, k, v = (jnp.asarray(rng.standard_normal((b, t, h, d)), jnp.bfloat16)
               for _ in range(3))
    mask = causal_mask(t)
    xla_fn = jax.jit(lambda q, k, v: attention(q, k, v, mask, jnp.bfloat16))
    out["prefill_attn_xla_ms"] = round(timeit(xla_fn, q, k, v), 2)
    if on_tpu:
        flash_fn = jax.jit(lambda q, k, v: flash_attention(q, k, v, causal=True))
        out["prefill_attn_flash_ms"] = round(timeit(flash_fn, q, k, v), 2)

    # paged decode attention: 8 rows, 128-page pool, 16-token pages
    bb, hh, hkv, dd, page, nb, pool = 8, 8, 4, 64, 16, 64, 513
    qd = jnp.asarray(rng.standard_normal((bb, hh, dd)), jnp.bfloat16)
    kp = jnp.asarray(rng.standard_normal((pool, page, hkv, dd)), jnp.bfloat16)
    vp = jnp.asarray(rng.standard_normal((pool, page, hkv, dd)), jnp.bfloat16)
    pt = jnp.asarray(rng.integers(1, pool, (bb, nb)), jnp.int32)
    lens = jnp.asarray(rng.integers(64, nb * page - 1, (bb,)), jnp.int32)
    gather_fn = jax.jit(
        lambda q, k, v, t_, l_: _paged_attn_xla(q[:, None], k, v, t_, l_, hh // hkv)
    )
    out["paged_attn_xla_gather_ms"] = round(timeit(gather_fn, qd, kp, vp, pt, lens), 2)
    if on_tpu:
        out["paged_attn_pallas_ms"] = round(
            timeit(lambda q, k, v, t_, l_: paged_attention(q, k, v, t_, l_),
                   qd, kp, vp, pt, lens), 2,
        )
    log(f"phase D kernels: {out}")
    return out


def ensure_live_backend(probe_timeout_s: float = 180.0) -> str:
    """Probe the default JAX backend in a SUBPROCESS before the parent
    initializes it. A remote-attached chip whose tunnel is wedged hangs the
    first device call indefinitely — observed in practice: the device served
    traffic for hours, then dispatch froze mid-session. A hung probe child is
    killable; a hung parent jax init is not. On failure the parent pins
    itself to CPU (JAX_PLATFORMS must be set before backend init) so the
    bench still produces an artifact, marked ``device_fallback``."""
    import subprocess

    accel_expected = bool(os.environ.get("PALLAS_AXON_POOL_IPS"))
    if not accel_expected and os.environ.get("JAX_PLATFORMS") == "cpu":
        return ""  # CPU-pinned smoke/CI runs: nothing to probe, no hang risk

    probe = (
        "import jax, numpy as np, jax.numpy as jnp\n"
        "f = jax.jit(lambda x: x + 1.0)\n"
        "np.asarray(f(jnp.zeros((1,), jnp.float32)))\n"
        "print(jax.default_backend())\n"
    )
    try:
        r = subprocess.run(
            [sys.executable, "-c", probe],
            capture_output=True, text=True, timeout=probe_timeout_s,
        )
        backend = r.stdout.strip().splitlines()[-1] if r.stdout.strip() else "?"
        if r.returncode == 0 and not (accel_expected and backend == "cpu"):
            log(f"backend probe ok: {backend}")
            return ""
        if r.returncode == 0:
            # the accelerator plugin swallowed its registration failure and
            # the child silently fell back to host CPU — mark it, or phase C
            # would report CPU numbers as device numbers
            reason = "accelerator plugin expected but child initialized cpu"
        else:
            reason = f"probe rc={r.returncode}: {r.stderr[-300:]}"
    except subprocess.TimeoutExpired:
        reason = f"probe hung >{probe_timeout_s:.0f}s (wedged device/tunnel)"
    log(f"backend probe FAILED ({reason}); falling back to CPU")
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    return reason


def main() -> None:
    t_start = time.perf_counter()
    fallback_reason = ensure_live_backend()
    if fallback_reason:
        warn_cpu_fallback(fallback_reason)
    # A wedged-device fallback means every phase runs on host CPU, where the
    # full-scale corpus/warmup alone exceed the driver budget (round 4: 402 s
    # embed + 742 s warmup → rc=124, no artifact). Downscale the MODELS and
    # heavy phases, NOT the sample size: BENCH_r05.json's n=4/c=2 produced a
    # statistically useless datapoint (one percentile pool of 4). Tiny
    # models keep 32 queries at concurrency 8 within the budget, so a
    # fallback artifact still has real p50/p95/occupancy. Explicit BENCH_*
    # env overrides below still win.
    fast = os.environ.get("BENCH_FAST") == "1" or bool(fallback_reason)
    n_queries = int(os.environ.get("BENCH_QUERIES", "24" if not fast else "32"))
    n_corpus = int(os.environ.get("BENCH_CORPUS", "2048" if not fast else "64"))
    new_tokens = int(os.environ.get("BENCH_NEW_TOKENS", "48" if not fast else "8"))
    concurrency = int(os.environ.get("BENCH_CONCURRENCY", "8"))
    # phase C inits >1B params — pointless (and driver-timeout-hostile) on
    # the CPU fallback path
    skip_scale = os.environ.get("BENCH_SKIP_SCALE") == "1" or fast
    serve_scale = os.environ.get("BENCH_SERVE_SCALE", "1b")
    scale_tokens = int(os.environ.get("BENCH_SCALE_TOKENS", "64"))
    # int8 KV pages in BOTH paged engines (phase A serving + phase C scale)
    kv_quant = os.environ.get("BENCH_KV_QUANT") or os.environ.get("KV_QUANT", "none")
    # sweep knob: run phase A at bf16 AND int8 on the same corpus/queries so
    # the footprint-vs-TPOT tradeoff lands in one artifact as measurement
    kv_sweep = os.environ.get("BENCH_KV_QUANT_SWEEP") == "1"

    import jax

    from sentio_tpu.config import Settings
    from sentio_tpu.models.llama import LlamaConfig
    from sentio_tpu.models.transformer import EncoderConfig

    devices = jax.devices()
    log(f"devices: {len(devices)} x {devices[0].platform} ({devices[0].device_kind})")
    rtt = phase_0_rtt()

    if fast:
        enc_cfg = EncoderConfig.tiny()
        llm_cfg = LlamaConfig.tiny()
    else:
        # MXU-friendly mini models: dims multiples of 128, bf16
        enc_cfg = EncoderConfig(
            vocab_size=512, dim=512, n_layers=8, n_heads=8, mlp_dim=2048, max_len=512
        )
        llm_cfg = LlamaConfig(
            vocab_size=512, dim=512, n_layers=12, n_heads=8, n_kv_heads=4,
            mlp_dim=1536, max_len=2048, rope_theta=500_000.0,
        )

    settings = Settings()
    settings.generator.max_new_tokens = new_tokens
    settings.generator.verifier_max_tokens = 64
    # ByteTokenizer ~ 1 token/char vs the selector's 4-chars/token heuristic:
    # keep assembled prompts inside the model window (see eval/runner.py)
    settings.generator.context_token_budget = max(
        (llm_cfg.max_len - new_tokens - 256) // 4, 32
    )

    docs = build_corpus(n_corpus)
    queries = [
        "What does the MXU systolic array do in bfloat16?",
        "How does JAX compile functions with XLA sharding?",
        "Explain BM25 term saturation and length normalization.",
        "How does ring all-reduce bandwidth scale across ICI?",
        "What fuses sparse and dense retrieval before generation?",
    ]

    rag = phase_a_rag(settings, enc_cfg, llm_cfg, docs, queries, n_queries,
                      new_tokens, concurrency, kv_quant=kv_quant)
    rag_int8 = None
    if kv_sweep and kv_quant == "none":
        rag_int8 = phase_a_rag(settings, enc_cfg, llm_cfg, docs, queries,
                               n_queries, new_tokens, concurrency,
                               kv_quant="int8")
    elif kv_sweep:
        log(f"BENCH_KV_QUANT_SWEEP ignored: KV_QUANT={kv_quant!r} already "
            f"pins the repr — unset it so the sweep can run bf16 AND int8")
    # verification-mode sweep (ISSUE 11): phase A once per VERIFY_MODE on
    # the same corpus/queries — sync pays the audit on the critical path,
    # async overlaps it with delivery, gated also skips it outright for
    # confident answers (BENCH_VERIFY_THRESHOLD overrides the gate)
    verify_sweep = None
    if os.environ.get("BENCH_VERIFY_SWEEP") == "1":
        from dataclasses import replace as _dc_replace

        sweep_settings = settings
        threshold_raw = os.environ.get("BENCH_VERIFY_THRESHOLD")
        if threshold_raw:
            sweep_settings = settings.with_overrides(
                generator=_dc_replace(
                    settings.generator,
                    verify_confidence_threshold=float(threshold_raw),
                ))
        # the sweep measures the LATENCY story (audit on vs off the
        # caller's critical path), so it runs lightly loaded by default:
        # under closed-loop saturation every mode is capacity-bound and
        # detached audits simply compete with the next query's decode —
        # throughput stays phase A's job. BENCH_VERIFY_CONCURRENCY raises
        # it for a contended sweep.
        sweep_conc = int(os.environ.get("BENCH_VERIFY_CONCURRENCY", "2"))
        verify_sweep = {}
        for mode in ("sync", "async", "gated"):
            log(f"phase VERIFY_SWEEP: verify_mode={mode} ...")
            r = phase_a_rag(sweep_settings, enc_cfg, llm_cfg, docs, queries,
                            n_queries, new_tokens, sweep_conc,
                            kv_quant=kv_quant, verify_mode=mode)
            verify_sweep[mode] = {
                "p50_ms": r["p50_ms"],
                "p95_ms": r["p95_ms"],
                "qps": r["qps"],
                "answer_p50_ms": r["verify"]["answer_ms"]["p50"],
                "verdict_p50_ms": r["verify"]["verdict_ms"]["p50"],
                "gate_skip_rate": r["verify"]["gate_skip_rate"],
            }
        log(f"phase VERIFY_SWEEP: {verify_sweep}")
    baseline = phase_b_baseline(docs, queries, n_queries, dim=enc_cfg.dim)
    baseline_wan = None if fast else phase_b_baseline(
        docs, queries, n_queries, dim=enc_cfg.dim,
        rtt_ms=float(os.environ.get("BENCH_BASELINE_RTT_MS", "40")),
    )
    scale = None if skip_scale else phase_c_scale(
        serve_scale, scale_tokens, 8, kv_quant=kv_quant
    )
    kernels = None if fast else phase_d_kernels()
    longctx = None if fast else phase_f_longctx()
    speculative = (
        phase_e_speculative(serve_scale, scale_tokens)
        if os.environ.get("BENCH_SPECULATIVE") == "1" and not skip_scale
        else None
    )
    # open-loop multi-replica load harness: LAST, so its collector swaps
    # cannot disturb the phases above
    load = phase_load(llm_cfg, new_tokens) \
        if os.environ.get("BENCH_LOAD") == "1" else None
    # replica-kill chaos drill: availability, incident-window p95, and
    # time-to-recover for a mid-run replica loss. BENCH_CHAOS_REPLICA_MODE
    # accepts a comma list (e.g. "thread,process") — the drill then runs
    # once per replica mode from this one invocation, and the chaos
    # section becomes a per-mode matrix
    chaos = None
    if os.environ.get("BENCH_CHAOS") == "1":
        chaos_modes = [m.strip().lower() for m in os.environ.get(
            "BENCH_CHAOS_REPLICA_MODE", "thread").split(",") if m.strip()]
        scenario = os.environ.get("BENCH_CHAOS_MODE", "kill").strip().lower()
        if len(chaos_modes) <= 1:
            chaos = phase_chaos(
                llm_cfg, new_tokens,
                replica_mode=(chaos_modes[0] if chaos_modes else "thread"))
        else:
            chaos = {
                "replica_mode_matrix": chaos_modes,
                "per_replica_mode": {
                    m: phase_chaos(llm_cfg, new_tokens, replica_mode=m)
                    for m in chaos_modes
                },
            }
        if scenario != "partition" and "socket" in chaos_modes:
            # socket replicas in the matrix: the half-open partition drill
            # rides along (it is the fault class the socket tier exists
            # for) — the artifact gains a dedicated `partition` section
            chaos["partition"] = phase_chaos(
                llm_cfg, new_tokens, replica_mode="socket",
                chaos_mode="partition")

    total_s = time.perf_counter() - t_start
    log(f"bench wall {total_s:.0f}s")

    payload = {
        "metric": "rag_chat_e2e_p50_latency",
        "value": rag["p50_ms"],
        "unit": "ms",
        # measured-vs-measured: the loopback architecture baseline on the
        # same corpus/queries (a LOWER bound for the reference — zero RTT,
        # zero model compute)
        "vs_baseline": round(baseline["p50_ms"] / max(rag["p50_ms"], 1e-9), 3),
        **rtt,
        **({"device_fallback": fallback_reason} if fallback_reason else {}),
        "rag": rag,
        **({"rag_int8": rag_int8} if rag_int8 else {}),
        **({"kv_quant_sweep": {
            "bf16_pool_hbm_bytes": rag["pool_hbm_bytes"],
            "int8_pool_hbm_bytes": rag_int8["pool_hbm_bytes"],
            "pool_ratio": round(
                rag_int8["pool_hbm_bytes"] / max(rag["pool_hbm_bytes"], 1), 4),
            "p50_ms_bf16": rag["p50_ms"],
            "p50_ms_int8": rag_int8["p50_ms"],
            "tpot_ms_bf16": rag.get("tpot_ms"),
            "tpot_ms_int8": rag_int8.get("tpot_ms"),
        }} if rag_int8 else {}),
        "baseline": baseline,
        **({"baseline_wan": baseline_wan} if baseline_wan else {}),
        **({"serve_scale": scale} if scale else {}),
        **({"kv_quant": kv_quant} if kv_quant != "none" else {}),
        **({"kernels": kernels} if kernels else {}),
        **({"longctx": longctx} if longctx else {}),
        **({"speculative": speculative} if speculative else {}),
        **({"verify_sweep": verify_sweep} if verify_sweep else {}),
        **({"load": load} if load else {}),
        **({"chaos": chaos} if chaos else {}),
        "wall_s": round(total_s, 1),
    }
    # platform stamped top-level AND into every phase section: a section
    # copied out of the artifact in isolation still names its platform
    plat = device_platform()
    payload["device_platform"] = plat
    for section in (rag, rag_int8, baseline, baseline_wan, scale, kernels,
                    longctx, speculative, load, chaos):
        if isinstance(section, dict):
            section["device_platform"] = plat
    # nested per-mode summaries stamped too (PR 12 known gap for
    # verify_sweep; the chaos replica-mode matrix gets the same treatment):
    # any sub-dict copied out of the artifact still names its platform
    if isinstance(verify_sweep, dict):
        for sub in verify_sweep.values():
            if isinstance(sub, dict):
                sub["device_platform"] = plat
    if isinstance(chaos, dict):
        for sub in (chaos.get("per_replica_mode") or {}).values():
            if isinstance(sub, dict):
                sub["device_platform"] = plat
        if isinstance(chaos.get("partition"), dict):
            chaos["partition"]["device_platform"] = plat
    print(json.dumps(payload))
    if fallback_reason:
        # repeated LAST so the banner cannot scroll away under phase logs
        warn_cpu_fallback(fallback_reason)


if __name__ == "__main__":
    main()
