"""End-to-end RAG serving benchmark — runs on whatever jax.devices() offers
(the driver runs it on one real TPU chip; CPU works for smoke tests).

Measures p50 end-to-end latency of the full retrieve → rerank → select →
generate → verify pipeline with EVERY model in-process on the device: the
bi-encoder embeds the query, the exact dense index matmuls over an in-HBM
corpus, BM25 scores host-side concurrently, the cross-encoder reranks, and
the decoder generates + self-audits. This is the pipeline the reference
serves over four remote HTTP hops (SURVEY.md §3.1).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
``vs_baseline`` is the speedup vs the only latency figure the reference
ships — its 2000 ms p95 alerting target (deploy/kubernetes/monitoring.yaml
there); >1.0 means faster. Details go to stderr.

Env knobs: BENCH_FAST=1 (tiny models, quick smoke), BENCH_QUERIES=N,
BENCH_CORPUS=N, BENCH_NEW_TOKENS=N.
"""

from __future__ import annotations

import json
import os
import sys
import time

REFERENCE_P95_TARGET_MS = 2000.0


def log(*args) -> None:
    print(*args, file=sys.stderr, flush=True)


def build_corpus(n: int) -> list:
    from sentio_tpu.models.document import Document

    topics = [
        ("tpu", "TPU v5e chips pair a 128x128 MXU systolic array with {i} MiB of VMEM; "
                "matmul throughput peaks in bfloat16 when tiles stay MXU-aligned."),
        ("jax", "JAX traces pure functions into XLA programs; version {i} introduced "
                "sharding improvements for pjit and shard_map collectives."),
        ("rag", "Retrieval augmented generation pipeline number {i} fuses BM25 with "
                "dense retrieval and reranks candidates before generation."),
        ("ir", "Classic information retrieval experiment {i} shows BM25 term "
               "saturation controlled by k1 and length normalization by b."),
        ("net", "Inter-chip interconnect study {i}: ring all-reduce bandwidth scales "
                "with torus links while DCN hops dominate cross-slice latency."),
    ]
    docs = []
    for i in range(n):
        key, template = topics[i % len(topics)]
        docs.append(
            Document(
                text=template.replace("{i}", str(i)),
                id=f"{key}-{i}",
                metadata={"source": f"{key}.md"},
            )
        )
    return docs


def main() -> None:
    t_start = time.perf_counter()
    fast = os.environ.get("BENCH_FAST") == "1"
    n_queries = int(os.environ.get("BENCH_QUERIES", "12" if not fast else "4"))
    n_corpus = int(os.environ.get("BENCH_CORPUS", "2048" if not fast else "64"))
    new_tokens = int(os.environ.get("BENCH_NEW_TOKENS", "48" if not fast else "8"))

    import jax

    from sentio_tpu.config import EmbedderConfig, GeneratorConfig, RerankConfig, Settings
    from sentio_tpu.graph.factory import GraphConfig, build_basic_graph
    from sentio_tpu.graph.state import create_initial_state
    from sentio_tpu.models.llama import LlamaConfig
    from sentio_tpu.models.transformer import EncoderConfig
    from sentio_tpu.ops.bm25 import BM25Index
    from sentio_tpu.ops.dense_index import TpuDenseIndex
    from sentio_tpu.ops.embedder import TpuEmbedder
    from sentio_tpu.ops.generator import LLMGenerator, TpuProvider
    from sentio_tpu.ops.reranker import CrossEncoderReranker
    from sentio_tpu.ops.retrievers import DenseRetriever, HybridRetriever, SparseRetriever
    from sentio_tpu.ops.verifier import AnswerVerifier
    from sentio_tpu.runtime.engine import GeneratorEngine

    devices = jax.devices()
    log(f"devices: {len(devices)} x {devices[0].platform} ({devices[0].device_kind})")

    if fast:
        enc_cfg = EncoderConfig.tiny()
        llm_cfg = LlamaConfig.tiny()
    else:
        # MXU-friendly mini models: dims multiples of 128, bf16
        enc_cfg = EncoderConfig(
            vocab_size=512, dim=512, n_layers=8, n_heads=8, mlp_dim=2048, max_len=512
        )
        llm_cfg = LlamaConfig(
            vocab_size=512, dim=512, n_layers=12, n_heads=8, n_kv_heads=4,
            mlp_dim=1536, max_len=2048, rope_theta=500_000.0,
        )

    settings = Settings()
    settings.generator.max_new_tokens = new_tokens
    settings.generator.context_token_budget = 1200

    log("building corpus + indexes ...")
    docs = build_corpus(n_corpus)
    embedder = TpuEmbedder(
        EmbedderConfig(provider="tpu", batch_size=128), model_config=enc_cfg
    )
    t0 = time.perf_counter()
    corpus_vecs = embedder.embed_many([d.text for d in docs])
    embed_s = time.perf_counter() - t0
    log(f"embedded {n_corpus} docs in {embed_s:.1f}s "
        f"({n_corpus / max(embed_s, 1e-9):.0f} docs/s)")

    dense_index = TpuDenseIndex(dim=enc_cfg.dim)
    dense_index.add(docs, corpus_vecs)
    bm25 = BM25Index().build(docs)

    retriever = HybridRetriever(
        retrievers=[DenseRetriever(embedder, dense_index), SparseRetriever(bm25)],
        config=settings.retrieval,
    )
    reranker = CrossEncoderReranker(
        RerankConfig(batch_size=32), model_config=enc_cfg
    )
    engine = GeneratorEngine(
        config=GeneratorConfig(model_preset="bench", max_new_tokens=new_tokens),
        model_config=llm_cfg,
    )
    generator = LLMGenerator(provider=TpuProvider(engine=engine), config=settings.generator)
    verifier = AnswerVerifier(generator=generator, config=settings.generator)

    graph = build_basic_graph(
        retriever, generator, reranker=reranker, verifier=verifier,
        config=GraphConfig(settings=settings),
    )

    queries = [
        "What does the MXU systolic array do in bfloat16?",
        "How does JAX compile functions with XLA sharding?",
        "Explain BM25 term saturation and length normalization.",
        "How does ring all-reduce bandwidth scale across ICI?",
        "What fuses sparse and dense retrieval before generation?",
    ]

    log("warmup (compilation) ...")
    t0 = time.perf_counter()
    graph.invoke(create_initial_state(queries[0], metadata={"mode": "fast"}))
    log(f"warmup done in {time.perf_counter() - t0:.1f}s")

    latencies = []
    for i in range(n_queries):
        q = queries[i % len(queries)]
        t0 = time.perf_counter()
        state = graph.invoke(create_initial_state(q, metadata={"mode": "fast"}))
        dt = (time.perf_counter() - t0) * 1000.0
        latencies.append(dt)
        log(f"  q{i}: {dt:.0f} ms  path={state['metadata']['graph_path']}")

    latencies.sort()
    p50 = latencies[len(latencies) // 2]
    p95 = latencies[min(int(len(latencies) * 0.95), len(latencies) - 1)]
    total_s = time.perf_counter() - t_start
    log(f"p50={p50:.0f}ms p95={p95:.0f}ms over {n_queries} queries; "
        f"bench wall {total_s:.0f}s")

    print(json.dumps({
        "metric": "rag_chat_e2e_p50_latency",
        "value": round(p50, 1),
        "unit": "ms",
        "vs_baseline": round(REFERENCE_P95_TARGET_MS / p50, 2),
    }))


if __name__ == "__main__":
    main()
